"""Device bitmap filter indexes (PR 12): packed-word bitwise kernels vs the
LUT-gather path, planner selectivity gating, and the one-snapshot fix for
`host_filter_mask` on consuming segments.

Every assertion here is differential: the bitmap path must be byte-identical
with the LUT path and with the host evaluator — the bitmap plane is a pure
performance representation, never a semantics change.
"""

import numpy as np
import pytest

from pinot_tpu.query.context import compile_query
from pinot_tpu.query.executor import ServerQueryExecutor, host_filter_mask
from pinot_tpu.query.planner import plan_segment, select_bitmap_leaves
from pinot_tpu.query.predicate import LutLeaf
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.segment.mutable import MutableSegment
from pinot_tpu.segment.reader import load_segment
from pinot_tpu.segment.writer import SegmentBuilder, SegmentGeneratorConfig

N = 2500
RNG = np.random.default_rng(20260805)

SCHEMA = Schema("bm", [
    dimension("region"), dimension("cat"),
    dimension("tags", single_value=False),
    metric("v", DataType.LONG), metric("x", DataType.DOUBLE),
])

REGIONS = [f"r{i}" for i in range(8)]
CATS = [f"c{i}" for i in range(5)]


def _columns(n=N, rng=None):
    rng = rng if rng is not None else np.random.default_rng(20260805)
    return {
        "region": [REGIONS[i] for i in rng.integers(0, len(REGIONS), n)],
        "cat": [CATS[i] for i in rng.integers(0, len(CATS), n)],
        "tags": [[f"t{j}" for j in rng.choice(6, rng.integers(1, 4),
                                              replace=False)] for _ in range(n)],
        "v": rng.integers(0, 1000, n).astype(np.int64),
        "x": np.round(rng.uniform(-10, 10, n), 3),
    }


@pytest.fixture(scope="module")
def indexed_segment(tmp_path_factory):
    out = tmp_path_factory.mktemp("bm_idx")
    return load_segment(SegmentBuilder(SCHEMA, SegmentGeneratorConfig(
        inverted_index_columns=["region", "cat"])).build(
            _columns(), str(out), "bm_0"))


@pytest.fixture(scope="module")
def plain_segment(tmp_path_factory):
    """Same data, NO auxiliary indexes — the 'indexes off' differential arm."""
    out = tmp_path_factory.mktemp("bm_plain")
    return load_segment(SegmentBuilder(SCHEMA, SegmentGeneratorConfig()).build(
        _columns(), str(out), "bm_1"))


# -- packed-word construction -------------------------------------------------

def test_bitmap_words_match_forward_column(indexed_segment):
    from pinot_tpu.engine.datablock import block_for
    block = block_for(indexed_segment)
    words = np.asarray(block.bitmap_words("region"))
    reader = indexed_segment.column("region")
    ids = np.asarray(reader.fwd)
    assert words.shape == (reader.cardinality, block.padded // 32)
    for dict_id in range(reader.cardinality):
        unpacked = np.unpackbits(
            words[dict_id].view(np.uint8), bitorder="little")
        np.testing.assert_array_equal(
            unpacked[:indexed_segment.num_docs].astype(bool), ids == dict_id)
        # padding rows must stay zero — popcount counts them otherwise
        assert not unpacked[indexed_segment.num_docs:].any()


def test_bitmap_words_declined_for_high_card_and_mv(indexed_segment):
    from pinot_tpu.engine.datablock import block_for
    block = block_for(indexed_segment)
    assert block.bitmap_words("tags") is None      # multi-value
    assert block.bitmap_words("v") is None         # no dict / numeric raw


# -- fused word-domain kernels ------------------------------------------------

WHERE_TREES = [
    "region = 'r1'",
    "region = 'r1' AND cat = 'c2'",
    "region = 'r1' OR cat = 'c2'",
    "NOT region = 'r1'",
    "NOT (region IN ('r1', 'r2') OR cat = 'c0')",
    "region IN ('r0', 'r3', 'r7') AND NOT cat IN ('c1', 'c4')",
]


def _all_bitmap_spec(seg, sql):
    """KernelSpec with EVERY LutLeaf forced onto the bitmap path."""
    from pinot_tpu.engine import kernels
    from pinot_tpu.engine.datablock import block_for
    ctx = compile_query(sql, SCHEMA)
    plan = plan_segment(ctx, seg)
    block = block_for(seg)
    bm = tuple(i for i, leaf in enumerate(plan.filter_prog.leaves)
               if isinstance(leaf, LutLeaf)
               and block.bitmap_words(leaf.col) is not None)
    plan.bitmap_leaves = bm
    spec = kernels.KernelSpec(plan.filter_prog, (), 1, (), {}, block.padded,
                              bitmap_leaves=bm)
    ex = ServerQueryExecutor()
    inputs = ex._kernel_inputs(plan, spec, block)
    return plan, spec, inputs


@pytest.mark.parametrize("where", WHERE_TREES)
def test_word_domain_mask_matches_host(indexed_segment, where):
    from pinot_tpu.engine import kernels
    sql = f"SELECT COUNT(*) FROM bm WHERE {where}"
    plan, spec, inputs = _all_bitmap_spec(indexed_segment, sql)
    assert spec.bitmap_index, "no bitmap leaves selected — test is vacuous"
    mask = kernels.compute_mask(spec, inputs)[:indexed_segment.num_docs]
    host = host_filter_mask(plan, indexed_segment)
    np.testing.assert_array_equal(np.asarray(mask), host)


@pytest.mark.parametrize("where", WHERE_TREES)
def test_popcount_filter_count_matches_mask(indexed_segment, where):
    from pinot_tpu.engine import kernels
    sql = f"SELECT COUNT(*) FROM bm WHERE {where}"
    plan, spec, inputs = _all_bitmap_spec(indexed_segment, sql)
    count = kernels.compute_filter_count(spec, inputs)
    assert count is not None, "all-bitmap tree must take the popcount path"
    assert count == int(host_filter_mask(plan, indexed_segment).sum())


def test_filter_count_declines_mixed_trees(indexed_segment):
    """A tree with a non-bitmap leaf cannot run fully in the word domain."""
    from pinot_tpu.engine import kernels
    from pinot_tpu.engine.datablock import block_for
    ctx = compile_query(
        "SELECT COUNT(*) FROM bm WHERE region = 'r1' AND v > 500", SCHEMA)
    plan = plan_segment(ctx, indexed_segment)
    block = block_for(indexed_segment)
    # only the low-card region leaf is bitmap-eligible; v's 1000-card dict is
    # not — exactly the mixed tree the popcount path must decline
    bm = tuple(i for i, leaf in enumerate(plan.filter_prog.leaves)
               if isinstance(leaf, LutLeaf)
               and block.bitmap_words(leaf.col) is not None)
    assert bm == (0,)
    spec = kernels.KernelSpec(plan.filter_prog, (), 1, (), {}, block.padded,
                              bitmap_leaves=bm)
    plan.bitmap_leaves = bm
    inputs = ServerQueryExecutor()._kernel_inputs(plan, spec, block)
    assert kernels.compute_filter_count(spec, inputs) is None
    # ...but the per-leaf unpack inside the full mask still agrees
    mask = kernels.compute_mask(spec, inputs)[:indexed_segment.num_docs]
    np.testing.assert_array_equal(np.asarray(mask),
                                  host_filter_mask(plan, indexed_segment))


# -- planner gating -----------------------------------------------------------

def test_select_bitmap_leaves_honors_selectivity_cap(indexed_segment):
    ctx = compile_query("SELECT COUNT(*) FROM bm WHERE region = 'r1'", SCHEMA)
    plan = plan_segment(ctx, indexed_segment)
    from pinot_tpu.engine import calibrate
    old = calibrate.get_caps()
    calibrate.set_caps(
        calibrate.KernelCaps(**{**old.__dict__, "bitmap_sel_cap": 0.5}))
    try:
        assert select_bitmap_leaves(plan, indexed_segment) == (0,)
        # a cap below the leaf's ~1/8 selectivity rejects it
        calibrate.set_caps(
            calibrate.KernelCaps(**{**old.__dict__, "bitmap_sel_cap": 0.01}))
        assert select_bitmap_leaves(plan, indexed_segment) == ()
    finally:
        calibrate.set_caps(old)


def test_select_bitmap_leaves_skips_mutable_segments():
    seg = MutableSegment("m", SCHEMA)
    for i in range(40):
        seg.index({"region": REGIONS[i % 8], "cat": CATS[i % 5],
                   "tags": ["t0"], "v": i, "x": 0.5})
    ctx = compile_query("SELECT COUNT(*) FROM bm WHERE region = 'r1'", SCHEMA)
    plan = plan_segment(ctx, seg)
    assert select_bitmap_leaves(plan, seg) == ()


# -- end-to-end differential: bitmap on/off/host, indexes on/off --------------

def _rand_where(rng):
    preds = []
    for _ in range(int(rng.integers(1, 4))):
        k = rng.integers(0, 5)
        if k == 0:
            preds.append(f"region = 'r{rng.integers(0, 10)}'")
        elif k == 1:
            vals = ", ".join(f"'c{rng.integers(0, 7)}'"
                             for _ in range(int(rng.integers(1, 4))))
            preds.append(f"cat IN ({vals})")
        elif k == 2:
            preds.append(f"v BETWEEN {rng.integers(0, 400)} "
                         f"AND {rng.integers(400, 1000)}")
        elif k == 3:
            preds.append(f"tags = 't{rng.integers(0, 7)}'")
        else:
            preds.append(f"NOT region IN ('r{rng.integers(0, 8)}', "
                         f"'r{rng.integers(0, 8)}')")
    glue = [" AND " if rng.random() < 0.6 else " OR "
            for _ in range(len(preds) - 1)]
    out = preds[0]
    for g, p in zip(glue, preds[1:]):
        out += g + p
    return out


def _sorted_rows(rows):
    return sorted(tuple(str(c) for c in r) for r in rows)


@pytest.mark.parametrize("seed", range(4))
def test_differential_bitmap_vs_lut_vs_host(indexed_segment, plain_segment,
                                            seed):
    rng = np.random.default_rng(4000 + seed)
    for qi in range(12):
        where = _rand_where(rng)
        sql = (f"SELECT region, COUNT(*), SUM(v) FROM bm WHERE {where} "
               f"GROUP BY region LIMIT 100000")
        want = None
        for seg in (indexed_segment, plain_segment):     # indexes on vs off
            for ex in (ServerQueryExecutor(bitmap_enabled=True),
                       ServerQueryExecutor(bitmap_enabled=False),
                       ServerQueryExecutor(use_device=False)):
                got = _sorted_rows(ex.execute([seg], sql).rows)
                if want is None:
                    want = got
                assert got == want, (
                    f"MISMATCH seed={seed} q={qi} bitmap={ex.bitmap_enabled} "
                    f"device={ex.use_device} "
                    f"indexed={seg is indexed_segment}\n{sql}")


def test_differential_consuming_segment(indexed_segment):
    """Consuming (mutable) segment answers match the committed form: bitmap
    selection is immutable-only, but the toggle must be inert, not wrong."""
    cols = _columns(600, np.random.default_rng(9))
    seg = MutableSegment("m", SCHEMA, inverted_index_columns=["region"])
    for i in range(600):
        seg.index({k: cols[k][i] for k in cols})
    rng = np.random.default_rng(55)
    for _ in range(8):
        sql = (f"SELECT cat, COUNT(*) FROM bm WHERE {_rand_where(rng)} "
               f"GROUP BY cat LIMIT 100000")
        want = None
        for ex in (ServerQueryExecutor(bitmap_enabled=True),
                   ServerQueryExecutor(bitmap_enabled=False),
                   ServerQueryExecutor(use_device=False)):
            got = _sorted_rows(ex.execute([seg], sql).rows)
            if want is None:
                want = got
            assert got == want, f"consuming mismatch: {sql}"


# -- host_filter_mask: one snapshot per leaf on consuming segments ------------

def test_host_filter_mask_survives_dict_id_remap():
    """Regression: the LUT is compiled against one dictionary snapshot; rows
    appended AFTER planning remap dict ids (the sorted dictionary inserts new
    values in the middle). host_filter_mask must bind the LUT, the inverted
    view, and the forward ids to ONE snapshot — mixing the stale compile-time
    LUT with fresh ids selects the wrong value."""
    seg = MutableSegment("m", SCHEMA, inverted_index_columns=["region"])
    for i in range(64):
        seg.index({"region": ["mm", "zz"][i % 2], "cat": "c0",
                   "tags": ["t0"], "v": i, "x": 0.0})
    ctx = compile_query("SELECT COUNT(*) FROM bm WHERE region = 'zz'", SCHEMA)
    plan = plan_segment(ctx, seg)   # LUT over dict ["mm", "zz"]: zz -> id 1
    # "aa" sorts FIRST: every existing id shifts (mm -> 1, zz -> 2)
    for i in range(32):
        seg.index({"region": "aa", "cat": "c0", "tags": ["t0"],
                   "v": 100 + i, "x": 0.0})
    mask = host_filter_mask(plan, seg)
    want = np.zeros(seg.num_docs, dtype=bool)
    want[1:64:2] = True             # the original zz rows, none of the aa rows
    np.testing.assert_array_equal(mask, want)
    # and the executor end-to-end agrees
    got = ServerQueryExecutor().execute([seg], ctx).rows
    assert got == [[32]]


def test_host_filter_mask_mv_snapshot_consistency():
    """Same remap hazard on the MV CSR arrays (flat ids + offsets)."""
    seg = MutableSegment("m", SCHEMA)
    for i in range(50):
        seg.index({"region": "r0", "cat": "c0",
                   "tags": ["mm"] if i % 2 else ["zz"], "v": i, "x": 0.0})
    ctx = compile_query("SELECT COUNT(*) FROM bm WHERE tags = 'zz'", SCHEMA)
    plan = plan_segment(ctx, seg)
    for i in range(30):
        seg.index({"region": "r0", "cat": "c0", "tags": ["aa"],
                   "v": 100 + i, "x": 0.0})
    mask = host_filter_mask(plan, seg)
    want = np.zeros(seg.num_docs, dtype=bool)
    want[0:50:2] = True
    np.testing.assert_array_equal(mask, want)


# -- the clusterConfig knob ---------------------------------------------------

def test_server_bitmap_knob_disables_executor_path(tmp_path):
    from pinot_tpu.cluster.catalog import Catalog
    from pinot_tpu.cluster.deepstore import LocalDeepStore
    from pinot_tpu.cluster.server import ServerNode
    catalog = Catalog()
    catalog.put_property("clusterConfig/server.index.bitmap.enabled", "false")
    deep = LocalDeepStore(str(tmp_path / "deep"))
    node = ServerNode("s0", catalog, deep, str(tmp_path / "s0"))
    assert node.executor.bitmap_enabled is False
    catalog2 = Catalog()
    node2 = ServerNode("s1", catalog2, deep, str(tmp_path / "s1"))
    assert node2.executor.bitmap_enabled is True
