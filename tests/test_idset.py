"""IdSet subsystem: IDSET aggregation, IN_ID_SET filter, broker IN_SUBQUERY rewrite.

Reference: IdSetAggregationFunction / InIdSetTransformFunction / subquery recursion at
BaseBrokerRequestHandler.java:782 (tested there by InIdSetQueriesTest).
"""

import numpy as np
import pytest

from pinot_tpu.cluster import QuickCluster
from pinot_tpu.query.executor import execute_query
from pinot_tpu.query.idset import IdSet, IdSetError
from pinot_tpu.segment import SegmentBuilder, SegmentGeneratorConfig, load_segment
from pinot_tpu.table import TableConfig

from conftest import make_ssb_columns


# -- IdSet unit behavior -----------------------------------------------------

def test_idset_roundtrip_int():
    s = IdSet.from_values(np.array([5, 1, 5, 9, 3], dtype=np.int64))
    back = IdSet.deserialize(s.serialize())
    assert back == s and back.kind == "i8" and len(back) == 4
    mask = back.contains(np.array([1, 2, 3, 9, 100]))
    assert mask.tolist() == [True, False, True, True, False]


def test_idset_roundtrip_float_and_str():
    f = IdSet.deserialize(IdSet.from_values(np.array([1.5, -2.25, 1.5])).serialize())
    assert f.kind == "f8" and f.contains(np.array([1.5, 0.0])).tolist() == [True, False]
    s = IdSet.deserialize(IdSet.from_values(["b", "a", "b", "c"]).serialize())
    assert s.kind == "str" and len(s) == 3
    assert s.contains(np.array(["a", "z"], dtype=object)).tolist() == [True, False]


def test_idset_union_and_promotion():
    a = IdSet.from_values(np.array([1, 2], dtype=np.int64))
    b = IdSet.from_values(np.array([2.5]))
    u = a.union(b)
    assert u.kind == "f8"
    assert u.contains(np.array([1.0, 2.5, 3.0])).tolist() == [True, True, False]
    with pytest.raises(IdSetError):
        a.union(IdSet.from_values(["x"]))


def test_idset_int_probe_float_column():
    # int set filtering a float column must match on numeric equality
    s = IdSet.from_values(np.array([2, 4], dtype=np.int64))
    assert s.contains(np.array([2.0, 2.5, 4.0])).tolist() == [True, False, True]


def test_idset_empty():
    e = IdSet.deserialize(IdSet.empty().serialize())
    assert len(e) == 0
    assert e.contains(np.array([1, 2])).tolist() == [False, False]


def test_idset_malformed_literal():
    with pytest.raises(IdSetError):
        IdSet.deserialize("not-a-real-idset")


# -- query path --------------------------------------------------------------

@pytest.fixture(scope="module")
def segments(tmp_path_factory, ssb_schema):
    rng = np.random.default_rng(11)
    out = tmp_path_factory.mktemp("idset_seg")
    builder = SegmentBuilder(ssb_schema, SegmentGeneratorConfig(
        inverted_index_columns=["lo_region"]))
    segs = []
    for i, n in enumerate((2500, 1500)):
        segs.append(load_segment(builder.build(make_ssb_columns(rng, n),
                                               str(out), f"lineorder_{i}")))
    return segs


def test_idset_agg_then_filter_string(segments):
    ser = execute_query(segments, "SELECT IDSET(lo_region) FROM lineorder "
                                  "WHERE lo_quantity < 10").rows[0][0]
    ids = IdSet.deserialize(ser)
    want = set()
    for seg in segments:
        r = seg.column("lo_region")
        q = seg.column("lo_quantity").values()
        want |= set(np.asarray(r.values(), dtype=object)[np.asarray(q) < 10])
    assert set(ids.values) == {str(w) for w in want}

    n_in = execute_query(
        segments, f"SELECT COUNT(*) FROM lineorder WHERE IN_ID_SET(lo_region, '{ser}')"
    ).rows[0][0]
    in_list = ", ".join(f"'{v}'" for v in sorted(want))
    n_want = execute_query(
        segments, f"SELECT COUNT(*) FROM lineorder WHERE lo_region IN ({in_list})"
    ).rows[0][0]
    assert n_in == n_want > 0


def test_idset_agg_then_filter_numeric(segments):
    ser = execute_query(segments, "SELECT IDSET(lo_custkey) FROM lineorder "
                                  "WHERE lo_discount >= 9").rows[0][0]
    ids = IdSet.deserialize(ser)
    assert ids.kind == "i8" and len(ids) > 0
    n = execute_query(
        segments, f"SELECT COUNT(*) FROM lineorder WHERE IN_ID_SET(lo_custkey, '{ser}')"
    ).rows[0][0]
    n_direct = execute_query(
        segments, "SELECT COUNT(DISTINCT lo_orderkey) FROM lineorder "
                  f"WHERE IN_ID_SET(lo_custkey, '{ser}')").rows[0][0]
    assert n > 0 and n_direct > 0
    # semi-join semantics: every row whose custkey had a >=9-discount order
    cust = np.concatenate([np.asarray(s.column("lo_custkey").values()) for s in segments])
    disc = np.concatenate([np.asarray(s.column("lo_discount").values()) for s in segments])
    want = int(np.isin(cust, np.unique(cust[disc >= 9])).sum())
    assert n == want


def test_in_id_set_not(segments):
    ser = execute_query(segments, "SELECT IDSET(lo_region) FROM lineorder "
                                  "WHERE lo_region = 'ASIA'").rows[0][0]
    total = execute_query(segments, "SELECT COUNT(*) FROM lineorder").rows[0][0]
    n_in = execute_query(
        segments, f"SELECT COUNT(*) FROM lineorder WHERE IN_ID_SET(lo_region, '{ser}')"
    ).rows[0][0]
    n_out = execute_query(
        segments,
        f"SELECT COUNT(*) FROM lineorder WHERE NOT IN_ID_SET(lo_region, '{ser}')"
    ).rows[0][0]
    assert n_in + n_out == total and n_in > 0 and n_out > 0


def test_idset_empty_result_filter(segments):
    ser = execute_query(segments, "SELECT IDSET(lo_region) FROM lineorder "
                                  "WHERE lo_quantity > 1000000").rows[0][0]
    assert len(IdSet.deserialize(ser)) == 0
    n = execute_query(
        segments, f"SELECT COUNT(*) FROM lineorder WHERE IN_ID_SET(lo_region, '{ser}')"
    ).rows[0][0]
    assert n == 0


# -- broker IN_SUBQUERY ------------------------------------------------------

def test_in_subquery_through_broker(tmp_path, ssb_schema):
    cluster = QuickCluster(num_servers=2, work_dir=str(tmp_path))
    cfg = TableConfig(ssb_schema.name, replication=1)
    cluster.create_table(ssb_schema, cfg)
    rng = np.random.default_rng(3)
    for _ in range(2):
        cluster.ingest_columns(cfg, make_ssb_columns(rng, 1200))

    # semi-join via subquery: customers that ever ordered in ASIA
    res = cluster.query(
        "SELECT COUNT(*) FROM lineorder WHERE IN_SUBQUERY(lo_custkey, "
        "'SELECT IDSET(lo_custkey) FROM lineorder WHERE lo_region = ''ASIA''')")
    direct = cluster.query("SELECT IDSET(lo_custkey) FROM lineorder "
                           "WHERE lo_region = 'ASIA'").rows[0][0]
    via_idset = cluster.query(
        f"SELECT COUNT(*) FROM lineorder WHERE IN_ID_SET(lo_custkey, '{direct}')")
    assert res.rows[0][0] == via_idset.rows[0][0] > 0


def test_in_subquery_bad_inner_query(tmp_path, ssb_schema):
    from pinot_tpu.query.context import QueryValidationError
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    cfg = TableConfig(ssb_schema.name, replication=1)
    cluster.create_table(ssb_schema, cfg)
    cluster.ingest_columns(cfg, make_ssb_columns(np.random.default_rng(1), 100))
    with pytest.raises(QueryValidationError):
        cluster.query("SELECT COUNT(*) FROM lineorder WHERE IN_SUBQUERY(lo_custkey, "
                      "'SELECT COUNT(*) FROM lineorder')")


def test_idset_string_with_embedded_nul():
    s = IdSet.from_values(["a\x00b", "plain", ""])
    back = IdSet.deserialize(s.serialize())
    assert back == s
    assert back.contains(np.array(["a\x00b", "a", ""], dtype=object)).tolist() \
        == [True, False, True]


def test_contains_int64_precision_above_2_53():
    import numpy as np
    from pinot_tpu.query.idset import IdSet

    # i8 set vs float probe: 2**53 + 1 is NOT float-representable; a float64
    # promotion would collapse it onto 2.0**53 and falsely match
    s = IdSet.from_values(np.array([2**53 + 1], dtype=np.int64))
    assert s.contains(np.array([2.0**53])).tolist() == [False]
    assert s.contains(np.array([float(2**54)])).tolist() == [False]
    assert s.contains(np.array([1.5])).tolist() == [False]
    # exactly-representable large ints still match through the float probe
    s2 = IdSet.from_values(np.array([2**54], dtype=np.int64))
    assert s2.contains(np.array([float(2**54)])).tolist() == [True]

    # f8 set vs int probe: the converse collapse
    f = IdSet.from_values(np.array([2.0**53]))
    assert f.contains(np.array([2**53 + 1], dtype=np.int64)).tolist() == [False]
    assert f.contains(np.array([2**53], dtype=np.int64)).tolist() == [True]
    # fractional set values never match int probes
    f2 = IdSet.from_values(np.array([2.5]))
    assert f2.contains(np.array([2], dtype=np.int64)).tolist() == [False]
