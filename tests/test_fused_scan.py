"""Differential suite for fused single-launch execution on compressed forms.

Three-way differential per query shape: the FUSED plan (single launch,
in-register dict/FOR decode), the STAGED plan (mask launch + aggregate launch
over decoded columns), and the host f64 oracle. Fused and staged run the same
f32 kernel regimes over the same row order, so their results must be
BYTE-IDENTICAL — any drift means the compressed-form decode changed a value.
The host comparison carries the usual f32-accumulation tolerance.

Covers the routing matrix: bitmap-only / mixed / NOT filter trees, null-heavy
columns, MV columns (value-column MV forces the staged rung; MV *filters*
stay fused), FOR-int and dict-encoded projections, and the stacked-burst
case where same-signature fused queries share one persistent launch.
"""

import numpy as np
import pytest

from pinot_tpu.engine import kernels
from pinot_tpu.engine.datablock import block_for, release_block
from pinot_tpu.query import stats as qstats
from pinot_tpu.query.executor import ServerQueryExecutor
from pinot_tpu.schema import (DataType, FieldRole, FieldSpec, Schema,
                              dimension, metric)
from pinot_tpu.segment.reader import load_segment
from pinot_tpu.segment.writer import SegmentBuilder, SegmentGeneratorConfig

N = 2400
RNG = np.random.default_rng(20260807)

SCHEMA = Schema("fused", [
    dimension("dim_a"), dimension("dim_b"),
    dimension("dim_i", DataType.INT),
    FieldSpec("tags", DataType.STRING, FieldRole.DIMENSION,
              single_value=False),
    metric("num_for", DataType.INT), metric("num_wide", DataType.INT),
    metric("val_x", DataType.DOUBLE), metric("val_null", DataType.DOUBLE),
])

COLS = {
    "dim_a": [f"a{i}" for i in RNG.integers(0, 8, N)],
    "dim_b": [f"b{i}" for i in RNG.integers(0, 5, N)],
    # dict-encoded int: the fused "dict" value form (in-register LUT gather)
    "dim_i": RNG.integers(0, 40, N).astype(np.int32) * 7,
    "tags": [[f"t{j}" for j in RNG.integers(0, 6, RNG.integers(1, 4))]
             for _ in range(N)],
    # range 200 < 2^8: uint8 FOR deltas vs int16 narrowed raw -> FOR form
    "num_for": RNG.integers(1000, 1200, N).astype(np.int32),
    # range >= 2^16: FOR declined -> raw passthrough stays fused
    "num_wide": RNG.integers(-(1 << 20), 1 << 20, N).astype(np.int32),
    "val_x": np.round(RNG.uniform(-100, 100, N), 3),
    # null-heavy: ~40% nulls through the writer's null bitmap
    "val_null": [None if RNG.random() < 0.4 else
                 round(float(RNG.uniform(0, 50)), 3) for _ in range(N)],
}


@pytest.fixture(scope="module")
def seg(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("fused")
    builder = SegmentBuilder(SCHEMA, SegmentGeneratorConfig(
        no_dictionary_columns=["num_for", "num_wide", "val_x", "val_null"]))
    return load_segment(builder.build(
        {k: (v.copy() if isinstance(v, np.ndarray) else list(v))
         for k, v in COLS.items()}, str(tmp), "fused_0"))


QUERIES = [
    # bitmap-only tree (dict IN/EQ leaves), FOR-int + raw projections
    ("SELECT dim_b, COUNT(*), SUM(num_for), MIN(num_wide) FROM fused "
     "WHERE dim_a IN ('a1', 'a2', 'a3') GROUP BY dim_b"),
    # mixed tree: dict leaf AND numeric compare (CmpLeaf value column)
    ("SELECT COUNT(*), SUM(val_x), MAX(num_for) FROM fused "
     "WHERE dim_a = 'a1' AND num_wide > 0"),
    # NOT over a compare, OR with a dict leaf
    ("SELECT dim_a, COUNT(*), SUM(num_for) FROM fused "
     "WHERE NOT num_for < 1100 OR dim_b = 'b2' GROUP BY dim_a"),
    # null-heavy value column: null rows drop out of SUM/COUNT identically
    ("SELECT dim_b, COUNT(val_null), SUM(val_null) FROM fused "
     "WHERE dim_a <> 'a0' GROUP BY dim_b"),
    # dict-encoded INT projection: the "dict" fused form feeds the aggregate
    ("SELECT dim_a, SUM(dim_i), MAX(dim_i) FROM fused "
     "WHERE num_for BETWEEN 1050 AND 1150 GROUP BY dim_a"),
    # MV filter (stacked id matrix) + SV aggregate: fused handles MV LUT
    # leaves — only MV *value* columns force the staged rung
    ("SELECT COUNT(*), SUM(num_for) FROM fused WHERE tags = 't1'"),
    # match-all: staged collapses to one launch, fused still one
    "SELECT SUM(num_wide), AVG(val_x) FROM fused",
]


def _rows(res):
    return sorted([tuple(r) for r in res.rows], key=lambda r: str(r))


@pytest.mark.parametrize("qi", range(len(QUERIES)))
def test_fused_vs_staged_byte_identical_vs_host(seg, qi):
    sql = QUERIES[qi]
    fused = ServerQueryExecutor(fused_enabled=True).execute([seg], sql)
    staged = ServerQueryExecutor(fused_enabled=False).execute([seg], sql)
    host = ServerQueryExecutor(use_device=False).execute([seg], sql)
    fr, sr, hr = _rows(fused), _rows(staged), _rows(host)
    assert fr == sr, f"fused != staged (byte-identical contract)\n{sql}"
    assert len(fr) == len(hr), sql
    for frow, hrow in zip(fr, hr):
        for fv, hv in zip(frow, hrow):
            if isinstance(fv, float) and isinstance(hv, float):
                assert fv == pytest.approx(hv, rel=1e-5, abs=0.05), sql
            else:
                assert fv == hv, sql


def test_fused_launch_count_halves_staged(seg):
    """Filter+aggregate: fused = 1 device launch, staged = 2 (mask +
    aggregate) — the >=2x launch-count reduction the issue pins."""
    sql = ("SELECT dim_b, COUNT(*), SUM(num_for) FROM fused "
           "WHERE dim_a = 'a1' AND num_wide > 0 GROUP BY dim_b")
    ServerQueryExecutor(fused_enabled=True).execute([seg], sql)   # warm jit
    ServerQueryExecutor(fused_enabled=False).execute([seg], sql)
    with qstats.collect_stats() as st_f:
        ServerQueryExecutor(fused_enabled=True).execute([seg], sql)
    with qstats.collect_stats() as st_s:
        ServerQueryExecutor(fused_enabled=False).execute([seg], sql)
    f_launches = int(st_f.counters.get(qstats.DEVICE_LAUNCHES, 0))
    s_launches = int(st_s.counters.get(qstats.DEVICE_LAUNCHES, 0))
    assert f_launches == 1, st_f.counters
    assert s_launches == 2, st_s.counters
    assert int(st_f.counters.get(qstats.FUSED_LAUNCHES, 0)) == 1
    assert int(st_s.counters.get(qstats.STAGED_LAUNCHES, 0)) == 2


def test_mv_value_column_degrades_to_staged(seg):
    """An MV aggregate argument cannot ride the fused forms; the plan must
    take the staged rung (or host), never a wrong fused answer."""
    sql = "SELECT COUNT(tags) FROM fused WHERE dim_a = 'a1'"
    ex = ServerQueryExecutor(fused_enabled=True)
    host = ServerQueryExecutor(use_device=False)
    got = ex.execute([seg], sql)
    want = host.execute([seg], sql)
    assert _rows(got) == _rows(want)


def test_for_form_eligibility(seg):
    """num_for (range 200, int16 raw) carries a FOR form; num_wide (range
    2^21) and the doubles do not."""
    block = block_for(seg)
    try:
        ff = block.for_form("num_for")
        assert ff is not None
        base, deltas = ff
        assert base == int(np.min(COLS["num_for"]))
        assert np.asarray(deltas).dtype == np.uint8
        assert block.for_form("num_wide") is None
        assert block.for_form("val_x") is None
    finally:
        release_block(seg)


def test_fused_spec_routes_expected_forms(seg):
    """The executor's routing decision itself: dict-SV value cols -> "dict",
    FOR-eligible raw ints -> "for", wide raw ints -> passthrough."""
    from pinot_tpu.query.context import compile_query
    from pinot_tpu.query.planner import plan_segment
    ex = ServerQueryExecutor(fused_enabled=True)
    ctx = compile_query(
        "SELECT SUM(dim_i), SUM(num_for), SUM(num_wide) FROM fused "
        "WHERE val_x > 0", seg.schema)
    plan = plan_segment(ctx, seg)
    assert plan.kind == "device"
    block = block_for(seg)
    try:
        routed = dict(ex._fused_cols(plan, seg, block))
        assert routed.get("dim_i") == "dict"
        assert routed.get("num_for") == "for"
        assert "num_wide" not in routed      # raw passthrough
        assert "val_x" not in routed         # raw float passthrough
    finally:
        release_block(seg)


def test_stacked_burst_one_launch_byte_identical(seg):
    """A burst of same-signature fused queries (different scalars) rides ONE
    stacked persistent launch; each answer matches its solo staged execution
    and the burst uses strictly fewer device launches than staged (which
    needs two per query)."""
    from pinot_tpu.parallel.combine import MeshQueryExecutor
    from pinot_tpu.query.aggregates import make_agg
    from pinot_tpu.query.context import compile_query
    from pinot_tpu.query.reduce import (merge_segment_results,
                                        reduce_to_result)
    thresholds = (0, 100_000, -250_000, 500_000)
    # dim_i is dict-encoded: SUM(dim_i) rides the mesh fused "dict" form
    sqls = [("SELECT COUNT(*), SUM(dim_i) FROM fused "
             f"WHERE num_wide > {t}") for t in thresholds]
    mex = MeshQueryExecutor()
    ctxs = [compile_query(sql, seg.schema) for sql in sqls]
    preps = [mex.prepare_partial(ctx, [seg]) for ctx in ctxs]
    assert all(p is not None for p in preps)
    assert any(p.spec.fused_cols for p in preps), \
        "burst should ride the fused compressed forms"
    # same signature + same block -> one stack key -> ONE batched launch
    with qstats.collect_stats() as st:
        launches = mex.dispatch_prepared(preps)
        assert len(launches) == 1, "same-signature burst must stack"
        outs_dev, finish, idxs = launches[0]
        assert sorted(idxs) == list(range(len(sqls)))
        outs_list = finish(mex.fetch([outs_dev])[0])
    burst_launches = int(st.counters.get(qstats.DEVICE_LAUNCHES, 0))
    assert burst_launches == 1, st.counters
    assert int(st.counters.get(qstats.FUSED_LAUNCHES, 0)) == 1

    staged = ServerQueryExecutor(fused_enabled=False)
    for pos, i in enumerate(idxs):
        partial = preps[i].decode(outs_list[pos])
        aggs = [make_agg(f) for f in ctxs[i].aggregations]
        got = reduce_to_result(
            ctxs[i], merge_segment_results([partial], aggs), aggs, []).rows
        want = staged.execute([seg], sqls[i])
        assert sorted(map(tuple, got)) == _rows(want), sqls[i]


def test_fused_kill_switch_env(seg, monkeypatch):
    """PINOT_TPU_FUSED=0 routes every plan down the staged rung."""
    from pinot_tpu.engine import calibrate
    monkeypatch.setenv("PINOT_TPU_FUSED", "0")
    calibrate.set_caps(None)  # force lazy re-resolution under the env var
    try:
        assert calibrate.get_caps().fused_enabled is False
        sql = "SELECT COUNT(*), SUM(num_for) FROM fused WHERE dim_a = 'a1'"
        with qstats.collect_stats() as st:
            ServerQueryExecutor().execute([seg], sql)
        assert int(st.counters.get(qstats.FUSED_LAUNCHES, 0)) == 0
        assert int(st.counters.get(qstats.STAGED_LAUNCHES, 0)) >= 1
    finally:
        monkeypatch.delenv("PINOT_TPU_FUSED")
        calibrate.set_caps(None)


def test_staged_spec_reuses_match_all_single_launch(seg):
    """A match-all filter needs no mask launch: staged executes in ONE
    launch and records stagedLaunches=1."""
    sql = "SELECT SUM(num_for), COUNT(*) FROM fused"
    ex = ServerQueryExecutor(fused_enabled=False)
    ex.execute([seg], sql)                     # warm
    with qstats.collect_stats() as st:
        ex.execute([seg], sql)
    assert int(st.counters.get(qstats.DEVICE_LAUNCHES, 0)) == 1
    assert int(st.counters.get(qstats.STAGED_LAUNCHES, 0)) == 1


def test_fused_signature_distinct_from_staged(seg):
    """fused_cols participates in KernelSpec.signature(): fused and staged
    plans must never share a jit cache entry."""
    from pinot_tpu.query.context import compile_query
    from pinot_tpu.query.planner import plan_segment
    ctx = compile_query(
        "SELECT SUM(num_for) FROM fused WHERE dim_a = 'a1'", seg.schema)
    plan = plan_segment(ctx, seg)
    block = block_for(seg)
    try:
        ex = ServerQueryExecutor(fused_enabled=True)
        fused_cols = ex._fused_cols(plan, seg, block)
        assert fused_cols  # num_for routes as ("num_for", "for")
        spec_fused = kernels.KernelSpec(
            plan.filter_prog, (), 1, (), {}, block.padded,
            fused_cols=fused_cols)
        spec_staged = kernels.KernelSpec(
            plan.filter_prog, (), 1, (), {}, block.padded)
        assert spec_fused.signature() != spec_staged.signature()
    finally:
        release_block(seg)
