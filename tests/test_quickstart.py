"""Quickstart + new CLI commands (reference: Quickstart family, ShowClusterInfo,
ChangeTableState, JsonToPinotSchema, LaunchDataIngestionJob)."""

import json

import numpy as np
import pytest

from pinot_tpu.schema import DataType, FieldRole
from pinot_tpu.tools.datagen import infer_schema


def test_infer_schema_jsonl(tmp_path):
    p = tmp_path / "rows.jsonl"
    p.write_text(json.dumps({"city": "nyc", "fare": 1.5, "n": 3,
                             "tags": ["a", "b"], "ts": 1_700_000_000_000}) + "\n" +
                 json.dumps({"city": "sf", "fare": 2.0, "n": 4,
                             "tags": ["c"], "ts": 1_700_000_100_000}) + "\n")
    s = infer_schema(str(p), table_name="trips")
    by_name = {f.name: f for f in s.fields}
    assert by_name["city"].data_type == DataType.STRING
    assert by_name["fare"].data_type == DataType.DOUBLE
    assert by_name["n"].data_type == DataType.INT
    assert by_name["tags"].single_value is False
    assert by_name["ts"].role == FieldRole.DATE_TIME
    assert s.name == "trips"


def test_infer_schema_csv(tmp_path):
    p = tmp_path / "rows.csv"
    p.write_text("k,v,big\na,1.5,9999999999\nb,2,123\n")
    s = infer_schema(str(p))
    by_name = {f.name: f for f in s.fields}
    assert by_name["k"].data_type == DataType.STRING
    assert by_name["v"].data_type == DataType.DOUBLE
    assert by_name["big"].data_type == DataType.LONG


def test_table_state_disable_enable(tmp_path):
    from pinot_tpu.cluster import QuickCluster
    from pinot_tpu.query.context import QueryValidationError
    from pinot_tpu.schema import Schema, dimension, metric
    from pinot_tpu.table import TableConfig
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    schema = Schema("t", [dimension("k"), metric("v", DataType.DOUBLE)])
    cfg = TableConfig("t")
    cluster.create_table(schema, cfg)
    cluster.ingest_columns(cfg, {"k": ["a"], "v": np.array([1.0])})
    assert cluster.query("SELECT COUNT(*) FROM t").rows[0][0] == 1

    cluster.controller.set_table_state("t_OFFLINE", enabled=False)
    with pytest.raises(QueryValidationError, match="disabled"):
        cluster.query("SELECT COUNT(*) FROM t")
    cluster.controller.set_table_state("t_OFFLINE", enabled=True)
    assert cluster.query("SELECT COUNT(*) FROM t").rows[0][0] == 1
    with pytest.raises(ValueError):
        cluster.controller.set_table_state("nope_OFFLINE", enabled=False)


def test_quickstart_batch_end_to_end(tmp_path, capsys):
    from pinot_tpu.tools.quickstart import run_quickstart
    rc = run_quickstart("batch", rows=500, work_dir=str(tmp_path),
                        exit_after_queries=True)
    assert rc == 0
    out = capsys.readouterr().out
    assert "SELECT COUNT(*) FROM trips" in out
    assert "500" in out
    assert "broker:" in out


def test_quickstart_hybrid_end_to_end(tmp_path, capsys):
    from pinot_tpu.tools.quickstart import run_quickstart
    rc = run_quickstart("hybrid", rows=400, work_dir=str(tmp_path),
                        exit_after_queries=True)
    assert rc == 0
    out = capsys.readouterr().out
    assert "600" in out  # 400 offline + 200 realtime rows


def test_ingest_job_cli(tmp_path):
    """LaunchDataIngestionJob over HTTP with a YAML spec."""
    from pinot_tpu.cluster.broker import Broker
    from pinot_tpu.cluster.catalog import Catalog
    from pinot_tpu.cluster.controller import Controller
    from pinot_tpu.cluster.deepstore import LocalDeepStore
    from pinot_tpu.cluster.remote import ControllerDeepStore, RemoteCatalog
    from pinot_tpu.cluster.server import ServerNode
    from pinot_tpu.cluster.services import (BrokerService, ControllerService,
                                            ServerService)
    from pinot_tpu.schema import Schema, dimension, metric
    from pinot_tpu.table import TableConfig
    from pinot_tpu.tools.admin import main
    from conftest import wait_until

    catalog = Catalog()
    ctrl = Controller("c0", catalog, LocalDeepStore(str(tmp_path / "ds")),
                      str(tmp_path / "c"))
    csvc = ControllerService(ctrl)
    cats = [RemoteCatalog(csvc.url, poll_timeout_s=1.0)]
    node = ServerNode("server_0", cats[0], ControllerDeepStore(csvc.url),
                      str(tmp_path / "s0"))
    ssvc = ServerService(node)
    cats.append(RemoteCatalog(csvc.url, poll_timeout_s=1.0))
    bsvc = BrokerService(Broker("b0", cats[1]))
    try:
        schema = Schema("jobs", [dimension("k"), metric("v", DataType.DOUBLE)])
        ctrl.add_schema(schema)
        ctrl.add_table(TableConfig("jobs"))
        data = tmp_path / "in.csv"
        data.write_text("k,v\na,1.0\nb,2.0\na,3.0\n")
        spec = tmp_path / "job.yaml"
        spec.write_text(f"table: jobs_OFFLINE\ninputPaths:\n  - {data}\n")
        rc = main(["ingest-job", "--controller", csvc.url, "--spec", str(spec)])
        assert rc == 0
        from pinot_tpu.cluster.process import BrokerClient
        bc = BrokerClient(bsvc.url)
        assert wait_until(lambda: bc.query("SELECT COUNT(*) FROM jobs")
                          ["resultTable"]["rows"][0][0] == 3)
        # cluster-info sees the table converged
        rc = main(["cluster-info", "--controller", csvc.url])
        assert rc == 0
    finally:
        for c in cats:
            c.close()
        for s in (csvc, ssvc, bsvc):
            s.stop()


def test_review_regressions(tmp_path):
    """Covers: later-row JSONL fields, int-only time-column guard, ms-exact
    calendar shifts, drop_table clearing operational flags."""
    import numpy as np
    from pinot_tpu.engine.expr import eval_expr
    from pinot_tpu.sql.parser import Parser

    # JSONL field appearing only in row 2 still infers
    p = tmp_path / "r.jsonl"
    p.write_text(json.dumps({"city": "nyc"}) + "\n" +
                 json.dumps({"city": "sf", "fare": 2.0}) + "\n")
    s = infer_schema(str(p))
    assert {f.name for f in s.fields} == {"city", "fare"}

    # non-integer explicit time column is rejected loudly
    p2 = tmp_path / "r2.jsonl"
    p2.write_text(json.dumps({"created_at": "2026-07-30", "v": 1}) + "\n")
    with pytest.raises(ValueError, match="time column"):
        infer_schema(str(p2), time_column="created_at")

    # ms-exact calendar shift (float timestamp() truncation dropped 1 ms)
    e = Parser("SELECT timestampadd('MONTH', 1, t) FROM x").parse().select[0][0]
    out = eval_expr(e, {"t": np.array([539656225879], dtype=np.int64)})
    assert int(out[0]) % 1000 == 879

    # drop_table clears disabled state
    from pinot_tpu.cluster import QuickCluster
    from pinot_tpu.schema import Schema, dimension, metric
    from pinot_tpu.table import TableConfig
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path / "cl"))
    schema = Schema("t2", [dimension("k"), metric("v", DataType.DOUBLE)])
    cluster.create_table(schema, TableConfig("t2"))
    cluster.controller.set_table_state("t2_OFFLINE", enabled=False)
    cluster.controller.drop_table("t2_OFFLINE")
    cluster.create_table(schema, TableConfig("t2"))
    cluster.ingest_columns(TableConfig("t2"), {"k": ["a"], "v": np.array([1.0])})
    assert cluster.query("SELECT COUNT(*) FROM t2").rows[0][0] == 1


def test_service_manager_all_roles_one_process(tmp_path):
    """Reference: PinotServiceManager — controller + server + broker in one
    process from one bootstrap; full ingest->query lifecycle works."""
    import numpy as np
    from pinot_tpu.cluster.process import BrokerClient, ControllerClient, \
        run_service_manager
    from pinot_tpu.schema import Schema, dimension, metric
    from pinot_tpu.segment.writer import SegmentBuilder
    from pinot_tpu.table import TableConfig
    from conftest import wait_until

    handles = run_service_manager(str(tmp_path / "work"), str(tmp_path / "run"),
                                  block=False)
    try:
        ctrl = ControllerClient(handles["controller"].url)
        schema = Schema("svc", [dimension("k"), metric("v", DataType.DOUBLE)])
        ctrl.add_schema(schema)
        ctrl.add_table(TableConfig("svc"))
        seg = SegmentBuilder(schema).build(
            {"k": ["a", "b"], "v": np.array([1.0, 2.0])},
            str(tmp_path / "b"), "svc_0")
        ctrl.upload_segment("svc_OFFLINE", seg)
        bc = BrokerClient(handles["broker"].url)
        assert wait_until(lambda: bc.query("SELECT SUM(v) FROM svc")
                          ["resultTable"]["rows"][0][0] == 3.0)
    finally:
        handles["minion"].stop()  # claim loop first: it polls the controller
        handles["server_obj"].shutdown()
        handles["controller_obj"].stop_periodic_tasks()
        for c in handles["catalogs"]:
            c.close()
        for role in ("controller", "server", "broker"):
            handles[role].stop()


def test_cluster_config_roundtrip(tmp_path):
    """Reference: OperateClusterConfig / /cluster/configs REST."""
    from pinot_tpu.cluster.catalog import Catalog
    from pinot_tpu.cluster.controller import Controller
    from pinot_tpu.cluster.deepstore import LocalDeepStore
    from pinot_tpu.cluster.http_service import get_json, post_json
    from pinot_tpu.cluster.services import ControllerService
    ctrl = Controller("c0", Catalog(), LocalDeepStore(str(tmp_path / "ds")),
                      str(tmp_path / "c"))
    csvc = ControllerService(ctrl)
    try:
        post_json(f"{csvc.url}/clusterConfigs",
                  {"key": "default.retention.days", "value": "30"})
        got = get_json(f"{csvc.url}/clusterConfigs")["clusterConfigs"]
        assert got == {"default.retention.days": "30"}
        post_json(f"{csvc.url}/clusterConfigs",
                  {"key": "default.retention.days", "value": None})
        assert get_json(f"{csvc.url}/clusterConfigs")["clusterConfigs"] == {}
    finally:
        csvc.stop()
