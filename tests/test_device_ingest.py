"""Device ingest plane differentials (PR 9): the vectorized consume→index
pipeline and device-resident consuming segments must be BIT-IDENTICAL to the
per-row host path everywhere a query can observe them.

Three layers of differential:

* reader surface — dictionaries, forward indexes, null bitmaps, min/max,
  MV offsets from `DeviceMutableSegment` vs the classic `MutableSegment`
  fed the same rows (per-row `index()` on the classic side);
* query results — integer aggregates byte-identical across the host relay
  AND the device pipeline, against both the frozen `ConsumingView` and the
  classic mutable segment;
* commit — segments built from `snapshot_arrays()` load back with the same
  data as ones built from the classic `snapshot_columns()`.

Plus the wire codec (PCB1 blocks) round-trip and the end-to-end kafkalite
block-stream pump.
"""

import json

import numpy as np
import pytest

from pinot_tpu.ingest.vectorized import (ColumnarBatch, decode_columnar_block,
                                         decode_columnar_blocks,
                                         encode_columnar_block)
from pinot_tpu.query.context import compile_query
from pinot_tpu.query.executor import ServerQueryExecutor
from pinot_tpu.schema import DataType, Schema, date_time, dimension, metric
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.segment.mutable import MutableSegment
from pinot_tpu.segment.mutable_device import DeviceMutableSegment


def _schema():
    return Schema("events", [
        dimension("site", DataType.STRING),
        metric("clicks", DataType.LONG),
        metric("cost", DataType.DOUBLE),
        metric("score", DataType.INT),
        date_time("ts", DataType.LONG)])


def _mv_schema():
    return Schema("tagged", [
        dimension("site", DataType.STRING),
        dimension("tags", DataType.STRING, single_value=False),
        dimension("codes", DataType.INT, single_value=False),
        metric("clicks", DataType.LONG),
        date_time("ts", DataType.LONG)])


def _rows(n, null_every=0, seed=7):
    rng = np.random.default_rng(seed)
    rows = []
    for i in range(n):
        r = {"site": f"s{int(rng.integers(0, 23))}.com",
             "clicks": int(rng.integers(-500, 5000)),
             "cost": float(np.round(rng.random() * 90, 4)),
             "score": int(rng.integers(-100, 100)),
             "ts": 1700000000000 + i}
        if null_every and i % null_every == 0:
            r["site"] = None
        if null_every and i % (null_every + 4) == 1:
            r["cost"] = None
        if null_every and i % (null_every + 7) == 2:
            r["score"] = None
        rows.append(r)
    return rows


def _fill(schema, rows):
    """rows -> coerced column lists (the per-row shape both stores accept)."""
    cols = {f.name: [r.get(f.name) for r in rows] for f in schema.fields}
    return cols


def _index_classic(schema, rows, name="seg0"):
    seg = MutableSegment(name, schema)
    for r in rows:
        seg.index(r)
    return seg


def _index_device(schema, rows, name="seg0", device_staging=False,
                  batch=None):
    seg = DeviceMutableSegment(name, schema, device_staging=device_staging)
    step = batch or len(rows) or 1
    for lo in range(0, len(rows), step):
        seg.index_batch(_fill(schema, rows[lo:lo + step]), coerced=True)
    return seg


def _assert_readers_equal(classic, dev, schema):
    assert classic.num_docs == dev.num_docs
    for f in schema.fields:
        a, b = classic.column(f.name), dev.column(f.name)
        assert a.meta == b.meta, (f.name, a.meta, b.meta)
        assert a.is_multi_value == b.is_multi_value
        assert a.has_dictionary == b.has_dictionary
        assert a.cardinality == b.cardinality, f.name
        assert np.array_equal(np.asarray(a.fwd), np.asarray(b.fwd)), f.name
        assert np.asarray(a.fwd).dtype == np.asarray(b.fwd).dtype, f.name
        if a.dictionary is not None or b.dictionary is not None:
            assert list(a.dictionary.values) == list(b.dictionary.values), \
                f.name
        na, nb = a.null_bitmap, b.null_bitmap
        assert (na is None) == (nb is None), f.name
        if na is not None:
            assert np.array_equal(np.asarray(na), np.asarray(nb)), f.name
        assert a.min_value == b.min_value, f.name
        assert a.max_value == b.max_value, f.name
        if a.is_multi_value:
            assert np.array_equal(a.mv_offsets, b.mv_offsets), f.name
            assert np.array_equal(a.mv_counts(), b.mv_counts()), f.name
    assert classic.snapshot_columns() == dev.snapshot_columns()


# -- reader-surface differentials ---------------------------------------------

def test_reader_surface_matches_per_row_path():
    schema = _schema()
    rows = _rows(1200, null_every=0)
    _assert_readers_equal(_index_classic(schema, rows),
                          _index_device(schema, rows, batch=257), schema)


def test_null_heavy_batches_match():
    schema = _schema()
    rows = _rows(900, null_every=3)
    _assert_readers_equal(_index_classic(schema, rows),
                          _index_device(schema, rows, batch=101), schema)


def test_multi_value_batches_match():
    schema = _mv_schema()
    rng = np.random.default_rng(11)
    rows = []
    for i in range(600):
        tags = [f"t{int(v)}" for v in rng.integers(0, 9, rng.integers(0, 4))]
        codes = [int(v) for v in rng.integers(0, 50, rng.integers(0, 3))]
        rows.append({"site": f"s{i % 5}", "tags": tags or None,
                     "codes": codes or None, "clicks": i,
                     "ts": 1700000000000 + i})
    _assert_readers_equal(_index_classic(schema, rows),
                          _index_device(schema, rows, batch=97), schema)


def test_dict_overflow_across_batches():
    """Dictionary growth across many batches: append-order ids must stay
    stable while the sorted dictionary reshuffles under them."""
    schema = Schema("wide", [dimension("k"), metric("v", DataType.LONG)])
    rows = [{"k": f"key_{(i * 37) % 5000:05d}", "v": i} for i in range(5000)]
    classic = _index_classic(schema, rows)
    dev = _index_device(schema, rows, batch=83)
    _assert_readers_equal(classic, dev, schema)
    assert dev.column("k").cardinality == classic.column("k").cardinality


def test_snapshot_frozen_at_intermediate_num_docs():
    """A view frozen mid-ingest must keep serving the FIRST n rows exactly
    even as later batches grow (and re-sort) the shared dictionary."""
    schema = Schema("t", [dimension("k"), metric("v", DataType.LONG)])
    rows = [{"k": f"z{i % 97}", "v": i} for i in range(400)]
    more = [{"k": f"a{i % 53}", "v": i} for i in range(300)]   # sorts BEFORE z*
    dev = _index_device(schema, rows, batch=100)
    view = dev.query_view()
    classic = _index_classic(schema, rows)
    dev.index_batch(_fill(schema, more), coerced=True)
    assert view.num_docs == 400
    for name in ("k", "v"):
        a, b = classic.column(name), view.column(name)
        assert np.array_equal(np.asarray(a.fwd), np.asarray(b.fwd)), name
        if a.dictionary is not None:
            assert list(a.dictionary.values) == list(b.dictionary.values)
    full = _index_classic(schema, rows + more)
    _assert_readers_equal(full, dev, schema)


# -- snapshot caching (satellite: per-num_docs caches) ------------------------

def test_snapshot_and_view_caches_key_on_num_docs():
    schema = _schema()
    rows = _rows(300)
    classic = _index_classic(schema, rows)
    s1 = classic.snapshot_columns()
    assert classic.snapshot_columns() is s1          # cached, same docs
    dev = _index_device(schema, rows)
    v1 = dev.query_view()
    assert dev.query_view() is v1
    dev.index_batch(_fill(schema, _rows(10, seed=9)), coerced=True)
    v2 = dev.query_view()
    assert v2 is not v1 and v2.num_docs == 310 and v1.num_docs == 300
    classic.index(_rows(1, seed=3)[0])
    assert classic.snapshot_columns() is not s1      # invalidated by growth


# -- wire codec ---------------------------------------------------------------

def test_wire_codec_round_trip():
    schema = _schema()
    rows = _rows(700, null_every=5)
    cols = _fill(schema, rows)
    blob = encode_columnar_block(schema, cols)
    cb = decode_columnar_block(blob)
    assert isinstance(cb, ColumnarBatch) and cb.n == 700
    dev = DeviceMutableSegment("seg0", schema)
    dev.index_arrays(cb)
    _assert_readers_equal(_index_classic(schema, rows), dev, schema)


def test_wire_codec_spliced_walk():
    schema = Schema("t", [dimension("k"), metric("v", DataType.LONG)])
    blocks = []
    for b in range(5):
        rows = [{"k": f"b{b}_{i % 7}", "v": b * 100 + i} for i in range(40)]
        blocks.append(encode_columnar_block(schema, _fill(schema, rows)))
    spliced = b"\n".join(blocks)
    batches = decode_columnar_blocks(spliced, len(blocks))
    assert [cb.n for cb in batches] == [40] * 5
    assert batches[3].max_of("v") == 339


def test_wire_codec_rejects_multi_value():
    schema = _mv_schema()
    with pytest.raises(ValueError):
        encode_columnar_block(schema, {f.name: [None] for f in schema.fields})


# -- array-native JSON decode -------------------------------------------------

def _native_available():
    from pinot_tpu.native import get_lib
    return get_lib() is not None


def test_json_array_native_differential():
    if not _native_available():
        pytest.skip("no C compiler for the native lib")
    from pinot_tpu.ingest.transform import columns_from_spliced_json
    from pinot_tpu.ingest.vectorized import columnar_batch_from_json
    schema = _schema()
    rows = _rows(1500, null_every=11)
    for r in rows[::13]:
        r.pop("cost", None)                 # missing key -> type-0 cell
    data = b",".join(json.dumps(r).encode() for r in rows)
    cb = columnar_batch_from_json(data, len(rows), schema)
    assert cb is not None, "array-native decode fell back"
    dev = DeviceMutableSegment("seg0", schema)
    dev.index_arrays(cb)
    classic = MutableSegment("seg0", schema)
    classic.index_batch(columns_from_spliced_json(data, len(rows), schema),
                        coerced=True)
    _assert_readers_equal(classic, dev, schema)


def test_json_array_native_falls_back_on_mixed_cells():
    if not _native_available():
        pytest.skip("no C compiler for the native lib")
    from pinot_tpu.ingest.vectorized import columnar_batch_from_json
    schema = _schema()
    rows = [{"site": "a", "clicks": "not-an-int", "cost": 1.0, "score": 1,
             "ts": 1}]
    data = json.dumps(rows[0]).encode()
    assert columnar_batch_from_json(data, 1, schema) is None


# -- query-result differentials (both transports) -----------------------------

_SQLS = (
    "SELECT COUNT(*), SUM(clicks), SUM(score) FROM events",
    "SELECT site, COUNT(*), SUM(clicks) FROM events GROUP BY site "
    "ORDER BY site LIMIT 100",
    "SELECT MIN(clicks), MAX(clicks), MIN(ts), MAX(ts) FROM events",
    "SELECT COUNT(*) FROM events WHERE clicks > 1000",
    "SELECT site, SUM(clicks) FROM events WHERE score >= 0 "
    "GROUP BY site ORDER BY site LIMIT 100",
)


def _run(seg, schema, sql, use_device):
    ctx = compile_query(sql, schema)
    return ServerQueryExecutor(use_device=use_device).execute([seg], ctx)


def test_query_results_identical_both_transports():
    """Integer aggregates must be BYTE-identical: classic mutable (host) vs
    frozen ConsumingView (host) vs device-staged view (device pipeline)."""
    schema = _schema()
    rows = _rows(2500, null_every=9)
    classic = _index_classic(schema, rows)
    dev_host = _index_device(schema, rows, batch=331)
    dev_staged = _index_device(schema, rows, batch=331, device_staging=True)
    hview = dev_host.query_view()
    sview = dev_staged.query_view()
    assert hview.is_mutable and not sview.is_mutable
    for sql in _SQLS:
        want = _run(classic, schema, sql, use_device=False).rows
        got_host = _run(hview, schema, sql, use_device=False).rows
        assert got_host == want, (sql, got_host, want)
        got_dev = _run(sview, schema, sql, use_device=True).rows
        assert got_dev == want, (sql, got_dev, want)


# -- commit (parallel segment build from columnar chunks) ---------------------

def test_commit_from_snapshot_arrays_matches(tmp_path):
    schema = _schema()
    rows = _rows(800, null_every=6)
    classic = _index_classic(schema, rows)
    dev = _index_device(schema, rows, batch=129)
    a = load_segment(SegmentBuilder(schema).build(
        classic.snapshot_columns(), str(tmp_path / "a"), "ev_a"))
    b = load_segment(SegmentBuilder(schema).build(
        dev.snapshot_arrays(), str(tmp_path / "b"), "ev_b"))
    assert a.num_docs == b.num_docs == 800
    for f in schema.fields:
        ca, cb = a.column(f.name), b.column(f.name)
        assert np.array_equal(np.asarray(ca.fwd), np.asarray(cb.fwd)), f.name
        if ca.dictionary is not None:
            assert list(ca.dictionary.values) == list(cb.dictionary.values)
    for sql in _SQLS:
        ra = _run(a, schema, sql, use_device=False).rows
        rb = _run(b, schema, sql, use_device=False).rows
        assert ra == rb, sql


def test_commit_multi_value_snapshot_arrays(tmp_path):
    schema = _mv_schema()
    rows = [{"site": f"s{i % 3}", "tags": [f"t{i % 4}", f"t{i % 6}"],
             "codes": [i % 9] if i % 5 else None, "clicks": i,
             "ts": 1700000000000 + i} for i in range(300)]
    dev = _index_device(schema, rows, batch=77)
    classic = _index_classic(schema, rows)
    a = load_segment(SegmentBuilder(schema).build(
        classic.snapshot_columns(), str(tmp_path / "a"), "mv_a"))
    b = load_segment(SegmentBuilder(schema).build(
        dev.snapshot_arrays(), str(tmp_path / "b"), "mv_b"))
    for name in ("tags", "codes"):
        ca, cb = a.column(name), b.column(name)
        assert np.array_equal(np.asarray(ca.fwd), np.asarray(cb.fwd)), name
        assert np.array_equal(ca.mv_offsets, cb.mv_offsets), name
        assert list(ca.dictionary.values) == list(cb.dictionary.values), name


# -- end-to-end: kafkalite columnar-block stream ------------------------------

def test_pump_end_to_end_block_stream(tmp_path):
    from pinot_tpu.cluster import QuickCluster
    from pinot_tpu.ingest.kafkalite import LogBrokerClient, LogBrokerServer
    from pinot_tpu.table import StreamConfig, TableConfig, TableType

    schema = _schema()
    srv = LogBrokerServer()
    try:
        client = LogBrokerClient(srv.bootstrap)
        client.create_topic("ev_blocks", 1)
        total, bs = 2000, 300
        rows = _rows(total, null_every=10, seed=13)
        payloads = [encode_columnar_block(schema,
                                          _fill(schema, rows[lo:lo + bs]))
                    for lo in range(0, total, bs)]
        client.produce_many("ev_blocks", payloads)
        cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
        cfg = TableConfig("events", table_type=TableType.REALTIME,
                          stream=StreamConfig(
                              stream_type="kafkalite", topic="ev_blocks",
                              decoder="columnar",
                              properties={"bootstrap": srv.bootstrap},
                              flush_threshold_rows=100_000))
        cluster.create_realtime_table(schema, cfg, num_partitions=1)
        cluster.pump_realtime(cfg.table_name_with_type)
        mgr = cluster.servers[0].realtime_manager(cfg.table_name_with_type)
        c = list(mgr.consumers.values())[0]
        assert c.last_decode_path == "blocks", c.last_decode_path
        assert isinstance(c.mutable, DeviceMutableSegment)
        assert c.mutable.num_docs == total
        res = cluster.query("SELECT COUNT(*), SUM(clicks) FROM events")
        assert res.rows[0][0] == total
        assert res.rows[0][1] == sum(r["clicks"] for r in rows)
    finally:
        srv.stop()


def test_pump_all_multi_partition(tmp_path):
    """pump_all drives every partition; per-partition lanes must not lose or
    double-count rows under the concurrent pump."""
    from pinot_tpu.cluster import QuickCluster
    from pinot_tpu.ingest.stream import MemoryStream
    from pinot_tpu.table import StreamConfig, TableConfig, TableType

    schema = _schema()
    MemoryStream.reset_all()
    parts = 4
    stream = MemoryStream.create("ev_mp", parts)
    rows = _rows(1600, seed=21)
    for i, r in enumerate(rows):
        stream.produce(json.dumps(r), partition=i % parts)
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    cfg = TableConfig("events", table_type=TableType.REALTIME,
                      stream=StreamConfig(stream_type="memory", topic="ev_mp",
                                          flush_threshold_rows=100_000))
    cluster.create_realtime_table(schema, cfg, num_partitions=parts)
    table = cfg.table_name_with_type
    for _ in range(6):
        cluster.pump_realtime(table)
    mgr = cluster.servers[0].realtime_manager(table)
    assert sum(c.mutable.num_docs for c in mgr.consumers.values()) == 1600
    res = cluster.query("SELECT COUNT(*), SUM(clicks) FROM events")
    assert res.rows[0][0] == 1600
    assert res.rows[0][1] == sum(r["clicks"] for r in rows)
