"""Kernel-regime calibration: cache persistence, validation, resolution order.

The caps that drive the group-by dispatch ladder (engine/calibrate.py) resolve
from defaults -> persisted cache -> optional micro-bench -> env overrides.
A corrupt or out-of-range cache must fall back WHOLESALE to defaults: a bogus
chunk_cap would silently mis-dispatch every group-by in the process.
"""

import json

import pytest

from pinot_tpu.engine import calibrate as cal


@pytest.fixture
def restore_caps():
    prev = cal.get_caps()
    yield
    cal.set_caps(prev)


def _caps(**kw):
    base = dict(matmul_cap=256, chunk_cap=65536, minmax_bcast_cap=512,
                high_card_regime="sorted", partition_block=512,
                source="calibrated")
    base.update(kw)
    return cal.KernelCaps(**base)


def test_cache_roundtrip(tmp_path):
    path = str(tmp_path / "caps.json")
    caps = _caps()
    cal.save_cached_caps(caps, path=path, key="cpu:test")
    loaded = cal.load_cached_caps(path=path, key="cpu:test")
    assert loaded is not None
    assert loaded.source == "cache"
    assert loaded.token() == caps.token()
    # a second platform's entry coexists in the same file
    cal.save_cached_caps(_caps(chunk_cap=8192), path=path, key="tpu:v5e")
    assert cal.load_cached_caps(path=path, key="cpu:test").chunk_cap == 65536
    assert cal.load_cached_caps(path=path, key="tpu:v5e").chunk_cap == 8192


def test_cache_unknown_platform_falls_back(tmp_path):
    path = str(tmp_path / "caps.json")
    cal.save_cached_caps(_caps(), path=path, key="cpu:test")
    assert cal.load_cached_caps(path=path, key="tpu:v99") is None


def test_bogus_cache_falls_back(tmp_path):
    missing = str(tmp_path / "nope.json")
    assert cal.load_cached_caps(path=missing, key="cpu:test") is None

    garbage = tmp_path / "garbage.json"
    garbage.write_text("{this is not json")
    assert cal.load_cached_caps(path=str(garbage), key="cpu:test") is None

    wrong_shape = tmp_path / "shape.json"
    wrong_shape.write_text(json.dumps({"cpu:test": {"matmul_cap": "huge"}}))
    assert cal.load_cached_caps(path=str(wrong_shape), key="cpu:test") is None


def test_out_of_range_cache_falls_back(tmp_path):
    path = tmp_path / "range.json"
    path.write_text(json.dumps({"cpu:test": {
        "matmul_cap": 7,  # below the validator floor
        "chunk_cap": 65536, "minmax_bcast_cap": 512,
        "high_card_regime": "sorted", "partition_block": 512}}))
    assert cal.load_cached_caps(path=str(path), key="cpu:test") is None

    path.write_text(json.dumps({"cpu:test": {
        "matmul_cap": 256, "chunk_cap": 65536, "minmax_bcast_cap": 512,
        "high_card_regime": "warp_speed",  # unknown regime
        "partition_block": 512}}))
    assert cal.load_cached_caps(path=str(path), key="cpu:test") is None

    path.write_text(json.dumps({"cpu:test": {
        "matmul_cap": 256, "chunk_cap": 65536, "minmax_bcast_cap": 512,
        "high_card_regime": "sorted",
        "partition_block": 1000}}))  # not a multiple of 64
    assert cal.load_cached_caps(path=str(path), key="cpu:test") is None


def test_get_caps_reads_persisted_cache(tmp_path, monkeypatch, restore_caps):
    path = str(tmp_path / "caps.json")
    caps = _caps(chunk_cap=32768)
    cal.save_cached_caps(caps, path=path)  # current platform key
    monkeypatch.setenv(cal.CACHE_ENV, path)
    cal.set_caps(None)  # force lazy re-resolution through the cache
    got = cal.get_caps()
    assert got.token() == caps.token()
    assert got.source == "cache"


def test_get_caps_bogus_cache_uses_defaults(tmp_path, monkeypatch,
                                            restore_caps):
    garbage = tmp_path / "garbage.json"
    garbage.write_text("][")
    monkeypatch.setenv(cal.CACHE_ENV, str(garbage))
    cal.set_caps(None)
    got = cal.get_caps()
    assert got.token() == cal.KernelCaps().token()
    assert got.source == "default"


def test_env_override_wins_over_cache(tmp_path, monkeypatch, restore_caps):
    path = str(tmp_path / "caps.json")
    cal.save_cached_caps(_caps(), path=path)
    monkeypatch.setenv(cal.CACHE_ENV, path)
    monkeypatch.setenv("PINOT_TPU_GROUPBY_REGIME", "partitioned")
    monkeypatch.setenv("PINOT_TPU_CHUNK_CAP", "8192")
    cal.set_caps(None)
    got = cal.get_caps()
    assert got.source == "env"
    assert got.high_card_regime == "partitioned"
    assert got.chunk_cap == 8192
    assert got.matmul_cap == 256  # untouched fields keep the cache values


def test_invalid_set_caps_rejected(restore_caps):
    with pytest.raises(ValueError):
        cal.set_caps(cal.KernelCaps(partition_block=100))  # not %64
    with pytest.raises(ValueError):
        cal.set_caps(cal.KernelCaps(high_card_regime="nope"))


def test_caps_change_kernel_signature(restore_caps):
    from pinot_tpu.engine.kernels import KernelSpec
    from pinot_tpu.query.predicate import FilterProgram

    spec = KernelSpec(FilterProgram(), ("k",), 8192, (), {}, 1024)
    sig_a = spec.signature()
    cal.set_caps(_caps(high_card_regime="partitioned"))
    sig_b = spec.signature()
    assert sig_a != sig_b  # caps token folds into the jit cache key
