"""Upsert + dedup tests (reference patterns: upsert metadata manager unit tests +
UpsertTableIntegrationTest / PartialUpsertTableIntegrationTest)."""

import json

import numpy as np
import pytest

from pinot_tpu.cluster import QuickCluster
from pinot_tpu.ingest.stream import MemoryStream
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.table import StreamConfig, TableConfig, TableType, UpsertConfig
from pinot_tpu.upsert import (PartitionDedupMetadataManager,
                              PartitionUpsertMetadataManager, merge_partial)


@pytest.fixture(autouse=True)
def _reset_streams():
    MemoryStream.reset_all()
    yield
    MemoryStream.reset_all()


def test_partition_upsert_manager_basics():
    m = PartitionUpsertMetadataManager()
    assert m.add_record("s1", 0, ("k1",), 10)
    assert m.add_record("s1", 1, ("k2",), 10)
    # replace k1 with a newer row in another segment
    assert m.add_record("s2", 0, ("k1",), 20)
    np.testing.assert_array_equal(m.valid_mask("s1", 2), [False, True])
    np.testing.assert_array_equal(m.valid_mask("s2", 1), [True])
    # out-of-order (older comparison value) is rejected
    assert not m.add_record("s2", 1, ("k1",), 5)
    np.testing.assert_array_equal(m.valid_mask("s2", 2), [True, False])
    assert m.num_primary_keys == 2


def test_dedup_manager():
    d = PartitionDedupMetadataManager()
    assert d.check_and_add(("a",))
    assert not d.check_and_add(("a",))
    assert d.check_and_add(("b",))


def test_merge_partial_strategies():
    assert merge_partial("OVERWRITE", 1, 2) == 2
    assert merge_partial("IGNORE", 1, 2) == 1
    assert merge_partial("INCREMENT", 1, 2) == 3
    assert merge_partial("MAX", 1, 2) == 2
    assert merge_partial("MIN", 1, 2) == 1
    assert merge_partial("APPEND", ["a"], "b") == ["a", "b"]
    assert merge_partial("UNION", ["a"], ["a", "b"]) == ["a", "b"]
    assert merge_partial("OVERWRITE", None, 5) == 5
    assert merge_partial("OVERWRITE", 5, None) == 5


def _upsert_schema():
    return Schema("orders", [
        dimension("order_id", DataType.STRING),
        dimension("status", DataType.STRING),
        metric("amount", DataType.DOUBLE),
    ], primary_key_columns=["order_id"])


def _make_cluster(tmp_path, upsert_cfg=None, dedup=False):
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    cfg = TableConfig("orders", table_type=TableType.REALTIME, replication=1,
                      stream=StreamConfig(stream_type="memory", topic="orders_topic",
                                          decoder="json", flush_threshold_rows=1000),
                      upsert=upsert_cfg, dedup_enabled=dedup)
    cluster.create_realtime_table(_upsert_schema(), cfg, 1)
    return cluster, cfg


def _produce(rows):
    stream = MemoryStream.get("orders_topic")
    for r in rows:
        stream.produce(json.dumps(r), partition=0)


def test_full_upsert_end_to_end(tmp_path):
    cluster, cfg = _make_cluster(tmp_path, UpsertConfig(mode="FULL"))
    table = cfg.table_name_with_type
    _produce([
        {"order_id": "o1", "status": "NEW", "amount": 10.0},
        {"order_id": "o2", "status": "NEW", "amount": 20.0},
        {"order_id": "o1", "status": "SHIPPED", "amount": 10.0},
        {"order_id": "o1", "status": "DELIVERED", "amount": 10.0},
    ])
    cluster.pump_realtime(table)
    res = cluster.query("SELECT COUNT(*), SUM(amount) FROM orders")
    assert res.rows[0][0] == 2  # one live row per key
    assert res.rows[0][1] == pytest.approx(30.0)
    res2 = cluster.query("SELECT status, COUNT(*) FROM orders GROUP BY status LIMIT 10")
    assert dict((r[0], r[1]) for r in res2.rows) == {"DELIVERED": 1, "NEW": 1}


def test_partial_upsert_increment(tmp_path):
    cluster, cfg = _make_cluster(tmp_path, UpsertConfig(
        mode="PARTIAL", partial_strategies={"amount": "INCREMENT",
                                            "status": "OVERWRITE"}))
    table = cfg.table_name_with_type
    _produce([
        {"order_id": "o1", "status": "NEW", "amount": 10.0},
        {"order_id": "o1", "status": "PAID", "amount": 5.0},
        {"order_id": "o1", "status": "PAID", "amount": 2.0},
    ])
    cluster.pump_realtime(table)
    res = cluster.query("SELECT status, SUM(amount) FROM orders GROUP BY status LIMIT 5")
    assert res.rows == [["PAID", 17.0]]


def test_dedup_end_to_end(tmp_path):
    cluster, cfg = _make_cluster(tmp_path, dedup=True)
    table = cfg.table_name_with_type
    _produce([
        {"order_id": "o1", "status": "NEW", "amount": 10.0},
        {"order_id": "o1", "status": "DUPLICATE", "amount": 99.0},
        {"order_id": "o2", "status": "NEW", "amount": 20.0},
    ])
    cluster.pump_realtime(table)
    res = cluster.query("SELECT COUNT(*), SUM(amount) FROM orders")
    assert res.rows[0][0] == 2  # duplicate dropped at ingest
    assert res.rows[0][1] == pytest.approx(30.0)


def test_upsert_survives_commit(tmp_path):
    """Valid-doc masks follow the segment across the mutable->immutable commit."""
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    cfg = TableConfig("orders", table_type=TableType.REALTIME, replication=1,
                      stream=StreamConfig(stream_type="memory", topic="orders_topic",
                                          decoder="json", flush_threshold_rows=4),
                      upsert=UpsertConfig(mode="FULL"))
    cluster.create_realtime_table(_upsert_schema(), cfg, 1)
    table = cfg.table_name_with_type
    _produce([
        {"order_id": "o1", "status": "NEW", "amount": 1.0},
        {"order_id": "o2", "status": "NEW", "amount": 2.0},
        {"order_id": "o1", "status": "PAID", "amount": 1.0},
        {"order_id": "o3", "status": "NEW", "amount": 3.0},
    ])
    for _ in range(4):
        cluster.pump_realtime(table)
    from pinot_tpu.cluster.catalog import STATUS_DONE
    metas = cluster.catalog.segments[table]
    assert any(m.status == STATUS_DONE for m in metas.values())
    # post-commit: update o2 in the new consuming segment
    _produce([{"order_id": "o2", "status": "CANCELLED", "amount": 2.0}])
    cluster.pump_realtime(table)
    res = cluster.query("SELECT COUNT(*) FROM orders")
    assert res.rows[0][0] == 3
    res2 = cluster.query("SELECT status, COUNT(*) FROM orders GROUP BY status LIMIT 10")
    assert dict((r[0], r[1]) for r in res2.rows) == \
        {"PAID": 1, "NEW": 1, "CANCELLED": 1}
