"""Chunk compression tests: codec roundtrips, chunked range reads, writer/
reader integration, query parity over compressed columns.

Reference pattern: ChunkCompressorFactory tests + V4 forward index reader
tests over each ChunkCompressionType.
"""

import os

import numpy as np
import pytest

from pinot_tpu.query.executor import ServerQueryExecutor
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.segment.compression import (CODECS, ChunkedArrayReader,
                                           write_chunked)
from pinot_tpu.segment.reader import load_segment
from pinot_tpu.segment.writer import SegmentBuilder, SegmentGeneratorConfig


@pytest.mark.parametrize("codec", sorted(CODECS))
def test_roundtrip_all_codecs(tmp_path, codec):
    arr = np.arange(200_000, dtype=np.int64) % 1000
    path = str(tmp_path / f"c_{codec}.bin")
    write_chunked(path, arr, codec=codec, chunk_rows=4096)
    r = ChunkedArrayReader(path)
    assert len(r) == len(arr) and r.codec == codec
    assert np.array_equal(r.array(), arr)
    if codec != "passthrough":
        assert os.path.getsize(path) < arr.nbytes // 4  # repetitive data shrinks


def test_range_reads_cross_chunks(tmp_path):
    arr = np.random.default_rng(1).random(10_000)
    path = str(tmp_path / "r.bin")
    write_chunked(path, arr, codec="zlib", chunk_rows=1000)
    r = ChunkedArrayReader(path)
    for lo, hi in [(0, 10), (995, 1005), (2999, 5001), (9990, 10_000),
                   (0, 10_000), (5000, 5000)]:
        assert np.array_equal(r.read_rows(lo, hi), arr[lo:hi]), (lo, hi)
    # out-of-range clamps
    assert np.array_equal(r.read_rows(-5, 3), arr[0:3])
    assert len(r.read_rows(9_999, 20_000)) == 1


def test_empty_and_single_chunk(tmp_path):
    for arr in [np.empty(0, dtype=np.float32), np.array([7], dtype=np.int32)]:
        path = str(tmp_path / f"e{len(arr)}.bin")
        write_chunked(path, arr, codec="lzma")
        r = ChunkedArrayReader(path)
        assert np.array_equal(r.array(), arr)


SCHEMA = Schema("m", [
    dimension("k", DataType.STRING),
    metric("v", DataType.DOUBLE),
    metric("big", DataType.LONG),
])


@pytest.fixture(scope="module", params=["zlib", "lzma"])
def seg_pair(tmp_path_factory, request):
    """(compressed, uncompressed) segments with identical data; raw columns
    forced via no_dictionary + high-cardinality values."""
    tmp = tmp_path_factory.mktemp(f"comp_{request.param}")
    rng = np.random.default_rng(3)
    cols = {"k": [f"k{i % 50}" for i in range(20_000)],
            "v": np.round(rng.random(20_000) * 100, 2),
            "big": rng.integers(0, 1 << 30, 20_000, dtype=np.int64)}
    plain = SegmentBuilder(SCHEMA, SegmentGeneratorConfig(
        no_dictionary_columns=["v", "big"])).build(dict(cols), str(tmp), "plain")
    comp = SegmentBuilder(SCHEMA, SegmentGeneratorConfig(
        no_dictionary_columns=["v", "big"],
        raw_compression=request.param)).build(dict(cols), str(tmp), "comp")
    return load_segment(comp), load_segment(plain)


def test_compressed_column_reads_identically(seg_pair):
    comp, plain = seg_pair
    for col in ("v", "big"):
        assert comp.column(col).meta.get("compression")
        assert np.array_equal(np.asarray(comp.column(col).fwd),
                              np.asarray(plain.column(col).fwd))
    # on-disk raw forward indexes are actually smaller
    def raw_size(seg, suffixes):
        cols_dir = os.path.join(seg.path, "cols")
        return sum(os.path.getsize(os.path.join(cols_dir, f))
                   for f in os.listdir(cols_dir)
                   if any(f.endswith(s) for s in suffixes) and
                   (f.startswith("v.") or f.startswith("big.")))
    assert raw_size(comp, [".fwdc.bin"]) < raw_size(plain, [".fwd.npy"])


@pytest.mark.parametrize("sql", [
    "SELECT SUM(v), COUNT(*) FROM m WHERE big > 536870912",
    "SELECT k, AVG(v) FROM m GROUP BY k ORDER BY k LIMIT 5",
    "SELECT k, v FROM m WHERE v < 1 ORDER BY v LIMIT 5",
])
def test_query_parity_compressed_vs_plain(seg_pair, sql):
    comp, plain = seg_pair
    for use_device in (True, False):
        ex = ServerQueryExecutor(use_device=use_device)
        assert ex.execute([comp], sql).rows == ex.execute([plain], sql).rows


def test_fwd_slicing_is_bounded(seg_pair):
    """reader.fwd[:n] on a compressed column decodes only the covering chunks
    (the dump tool's bounded-read contract)."""
    comp, plain = seg_pair
    comp = load_segment(comp.path)  # fresh readers: no cached full decode
    r = comp.column("v").fwd
    assert r._full is None
    head = r[:7]
    assert np.array_equal(head, np.asarray(plain.column("v").fwd)[:7])
    assert r._full is None, "a head slice must not trigger a full decode"
    # full materialization still works and caches
    assert len(np.asarray(r)) == 20_000
    assert r._full is not None


def test_verify_segment_handles_compressed(seg_pair):
    from pinot_tpu.tools.segment import verify_segment
    comp, _ = seg_pair
    report = verify_segment(comp.path)
    assert report["ok"], report
