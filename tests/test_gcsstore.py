"""GCS-wire deep store: JSON-API client + stub, auth, cluster chaos.

Mirrors the reference's GCS plugin coverage
(`pinot-plugins/pinot-file-system/pinot-gcs/src/test/...`) with the same
proof pattern as test_s3store.py."""

import json
import time

import numpy as np
import pytest

from pinot_tpu.cluster.deepstore import create_fs
from pinot_tpu.cluster.gcsstore import GcsDeepStoreFS, GcsError, GcsStub
from pinot_tpu.schema import DataType, Schema, date_time, dimension, metric
from pinot_tpu.table import StreamConfig, TableConfig, TableType

from conftest import wait_until


@pytest.fixture
def stub():
    s = GcsStub(bucket="pinot", token="tok123")
    yield s
    s.stop()


def test_gcs_fs_contract(stub, tmp_path):
    fs = create_fs(stub.spec())
    assert isinstance(fs, GcsDeepStoreFS)
    fs.put_bytes(b"hello", "t/seg0.tar.gz")
    assert fs.get_bytes("t/seg0.tar.gz") == b"hello"
    assert fs.exists("t/seg0.tar.gz") and fs.exists("t")
    assert not fs.exists("t/nope")
    src = tmp_path / "blob"
    src.write_bytes(b"\x00\x01" * 500)
    fs.upload(str(src), "t/seg1.tar.gz")
    dst = tmp_path / "out" / "blob"
    fs.download("t/seg1.tar.gz", str(dst))
    assert dst.read_bytes() == src.read_bytes()
    fs.put_bytes(b"x", "t/sub/inner.bin")
    assert fs.listdir("t") == ["seg0.tar.gz", "seg1.tar.gz", "sub"]
    fs.move("t/seg0.tar.gz", "moved/seg0.tar.gz")
    assert not fs.exists("t/seg0.tar.gz")
    assert fs.get_bytes("moved/seg0.tar.gz") == b"hello"
    fs.delete("t")
    assert not fs.exists("t/seg1.tar.gz") and not fs.exists("t/sub/inner.bin")
    with pytest.raises(FileNotFoundError):
        fs.get_bytes("t/seg1.tar.gz")


def test_gcs_auth_and_pagination(stub):
    bad = create_fs(f"gs://pinot?endpoint={stub.url}&token=WRONG")
    with pytest.raises(GcsError):
        bad.put_bytes(b"x", "k")
    fs = create_fs(stub.spec("pg") + "&pageSize=7")
    for i in range(25):
        fs.put_bytes(b"x", f"d/k{i:03d}")
    fs.put_bytes(b"y", "d/sub/inner")
    assert len(fs._list("pg/d/", "")) == 26
    names = fs.listdir("d")
    assert len(names) == 26 and "sub" in names
    # mid-outage delete raises instead of silently succeeding
    stub.outage = True
    try:
        with pytest.raises(GcsError):
            fs.delete("d")
    finally:
        stub.outage = False
    assert fs.exists("d/k000")


def test_process_cluster_on_gcs_with_outage_heals(tmp_path):
    """ProcessCluster storing realtime segments through gs://; a GCS outage
    mid-stream commits via peer download and heals after recovery (mirror of
    the S3 chaos flow — one deep-store SPI, two cloud wires)."""
    from pinot_tpu.cluster.http_service import post_json
    from pinot_tpu.cluster.process import ProcessCluster
    from pinot_tpu.ingest.kafkalite import LogBrokerClient, LogBrokerServer

    stub = GcsStub(bucket="pinot", token="tok123")
    srv = LogBrokerServer()
    try:
        client = LogBrokerClient(srv.bootstrap)
        client.create_topic("gt", 1)
        cfg_path = tmp_path / "cluster.conf"
        cfg_path.write_text(f"controller.deepstore={stub.spec('deepstore')}\n")
        schema = Schema("gt", [
            dimension("u", DataType.STRING), metric("v", DataType.LONG),
            date_time("ts", DataType.LONG)])
        with ProcessCluster(num_servers=2, work_dir=str(tmp_path),
                            config_path=str(cfg_path)) as cluster:
            cluster.controller.add_schema(schema)
            cfg = TableConfig(
                "gt", table_type=TableType.REALTIME, time_column="ts",
                replication=2,
                stream=StreamConfig(stream_type="kafkalite", topic="gt",
                                    properties={"bootstrap": srv.bootstrap},
                                    flush_threshold_rows=25))
            cluster.controller.add_table(cfg, num_partitions=1)
            table = cfg.table_name_with_type

            def count():
                rows = cluster.query(
                    "SELECT COUNT(*) FROM gt")["resultTable"]["rows"]
                return rows[0][0] if rows else 0

            for i in range(30):
                client.produce("gt", json.dumps(
                    {"u": f"u{i % 3}", "v": i, "ts": 1700000000000 + i}))
            assert wait_until(lambda: count() == 30, timeout=60)

            def done_segments():
                metas = cluster.controller.segments_meta(table)["segments"]
                return {n: m for n, m in metas.items()
                        if m.get("status") == "DONE"}
            assert wait_until(lambda: len(done_segments()) >= 1, timeout=60)
            assert any(k.endswith(".tar.gz") for k in stub.objects)

            stub.outage = True
            try:
                for i in range(30, 60):
                    client.produce("gt", json.dumps(
                        {"u": f"u{i % 3}", "v": i, "ts": 1700000000000 + i}))
                assert wait_until(
                    lambda: any(str(m.get("download_path", "")).startswith(
                        "peer://") for m in done_segments().values()),
                    timeout=90), "commit must survive the GCS outage"
                assert wait_until(lambda: count() == 60, timeout=60)
            finally:
                stub.outage = False

            peer_segs = [n for n, m in done_segments().items()
                         if str(m.get("download_path", "")
                                ).startswith("peer://")]
            healed = post_json(f"{cluster.controller_url}/validate", {})
            assert set(peer_segs) <= set(healed.get("healed", [])), healed
    finally:
        srv.stop()
        stub.stop()
