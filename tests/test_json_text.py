"""JSON index (JSON_MATCH, JSON_EXTRACT_SCALAR) and text index (TEXT_MATCH) correctness.

Reference analogs: JsonIndexTest / JsonMatchPredicateTest and the text index suites
(LuceneTextIndexReader/NativeTextIndexReader tests). Index-backed results are asserted
equal to the index-free scan fallback and to expected row sets computed in python.
"""

import json

import numpy as np
import pytest

from pinot_tpu.query.executor import execute_query
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.segment import SegmentBuilder, SegmentGeneratorConfig, load_segment


@pytest.fixture(scope="module")
def jenv(tmp_path_factory):
    rng = np.random.default_rng(5)
    n = 500
    names = ["alice", "bob", "carol", "dan"]
    cities = ["sf", "nyc", "sea"]
    docs = []
    for i in range(n):
        d = {
            "name": names[rng.integers(0, len(names))],
            "age": int(rng.integers(18, 80)),
            "addr": {"city": cities[rng.integers(0, len(cities))],
                     "zip": str(10000 + int(rng.integers(0, 100)))},
            "tags": [f"t{int(t)}" for t in rng.integers(0, 6, rng.integers(0, 4))],
        }
        if i % 7 == 0:
            del d["addr"]
        docs.append(d)
    texts = []
    corpus = ["quick brown fox", "lazy dog sleeps", "brown dog barks loudly",
              "the quick red fox jumps", "silent night", "java query engine",
              "distributed query engine rocks"]
    for i in range(n):
        texts.append(corpus[rng.integers(0, len(corpus))])

    schema = Schema("people", [
        dimension("js", DataType.JSON),
        dimension("doc", DataType.STRING),
        metric("score", DataType.INT),
    ])
    cols = {
        "js": [json.dumps(d) for d in docs],
        "doc": texts,
        "score": rng.integers(0, 100, n).astype(np.int32),
    }
    out = tmp_path_factory.mktemp("jseg")
    seg = load_segment(SegmentBuilder(schema, SegmentGeneratorConfig(
        json_index_columns=["js"], text_index_columns=["doc"])).build(
        cols, str(out), "people_0"))
    # a second segment without indexes: exercises the scan fallback on the same data
    seg_noidx = load_segment(SegmentBuilder(schema, SegmentGeneratorConfig()).build(
        cols, str(out), "people_1"))
    return seg, seg_noidx, docs, texts, cols


def count_where(docs, pred):
    return sum(1 for d in docs if pred(d))


def q_count(seg, sql):
    return int(execute_query([seg], sql).rows[0][0])


def test_json_match_eq(jenv):
    seg, seg_noidx, docs, _, _ = jenv
    sql = "SELECT COUNT(*) FROM people WHERE JSON_MATCH(js, '\"$.name\" = ''alice''')"
    want = count_where(docs, lambda d: d["name"] == "alice")
    assert q_count(seg, sql) == want
    assert q_count(seg_noidx, sql) == want


def test_json_match_nested_and(jenv):
    seg, seg_noidx, docs, _, _ = jenv
    sql = ("SELECT COUNT(*) FROM people WHERE "
           "JSON_MATCH(js, '\"$.addr.city\" = ''sf'' AND \"$.age\" > 40')")
    want = count_where(docs, lambda d: d.get("addr", {}).get("city") == "sf"
                       and d["age"] > 40)
    assert q_count(seg, sql) == want
    assert q_count(seg_noidx, sql) == want


def test_json_match_array_element(jenv):
    seg, seg_noidx, docs, _, _ = jenv
    sql = "SELECT COUNT(*) FROM people WHERE JSON_MATCH(js, '\"$.tags[*]\" = ''t3''')"
    want = count_where(docs, lambda d: "t3" in d["tags"])
    assert q_count(seg, sql) == want
    assert q_count(seg_noidx, sql) == want


def test_json_match_is_null_presence(jenv):
    seg, _, docs, _, _ = jenv
    sql = "SELECT COUNT(*) FROM people WHERE JSON_MATCH(js, '\"$.addr.city\" IS NULL')"
    want = count_where(docs, lambda d: "addr" not in d)
    assert q_count(seg, sql) == want


def test_json_match_in_and_range(jenv):
    seg, _, docs, _, _ = jenv
    sql = ("SELECT COUNT(*) FROM people WHERE "
           "JSON_MATCH(js, '\"$.addr.city\" IN (''sf'', ''nyc'')')")
    want = count_where(docs, lambda d: d.get("addr", {}).get("city") in ("sf", "nyc"))
    assert q_count(seg, sql) == want
    sql2 = "SELECT COUNT(*) FROM people WHERE JSON_MATCH(js, '\"$.age\" BETWEEN 30 AND 40')"
    want2 = count_where(docs, lambda d: 30 <= d["age"] <= 40)
    assert q_count(seg, sql2) == want2


def test_json_match_combined_with_other_filter(jenv):
    seg, _, docs, _, cols = jenv
    sql = ("SELECT COUNT(*) FROM people WHERE "
           "JSON_MATCH(js, '\"$.name\" = ''bob''') AND score >= 50")
    want = sum(1 for i, d in enumerate(docs)
               if d["name"] == "bob" and cols["score"][i] >= 50)
    assert q_count(seg, sql) == want


def test_json_match_group_by(jenv):
    seg, _, docs, _, _ = jenv
    res = execute_query([seg], "SELECT COUNT(*) FROM people WHERE "
                        "JSON_MATCH(js, '\"$.age\" > 50') GROUP BY doc")
    total = sum(int(r[0]) for r in res.rows)
    assert total == count_where(docs, lambda d: d["age"] > 50)


def test_json_extract_scalar(jenv):
    seg, _, docs, _, _ = jenv
    res = execute_query(
        [seg], "SELECT JSON_EXTRACT_SCALAR(js, '$.age', 'INT', 0) FROM people LIMIT 500")
    got = [int(r[0]) for r in res.rows]
    assert got == [d["age"] for d in docs]


def test_json_extract_scalar_missing_default(jenv):
    seg, _, docs, _, _ = jenv
    res = execute_query(
        [seg],
        "SELECT JSON_EXTRACT_SCALAR(js, '$.addr.city', 'STRING', 'none') FROM people LIMIT 500")
    got = [r[0] for r in res.rows]
    want = [d.get("addr", {}).get("city", "none") for d in docs]
    assert got == want


# -- text index ---------------------------------------------------------------

def test_text_match_term(jenv):
    seg, seg_noidx, _, texts, _ = jenv
    sql = "SELECT COUNT(*) FROM people WHERE TEXT_MATCH(doc, 'fox')"
    want = sum(1 for t in texts if "fox" in t.split())
    assert q_count(seg, sql) == want
    assert q_count(seg_noidx, sql) == want


def test_text_match_and_or_not(jenv):
    seg, _, _, texts, _ = jenv
    assert q_count(seg, "SELECT COUNT(*) FROM people WHERE TEXT_MATCH(doc, 'quick AND fox')") \
        == sum(1 for t in texts if "quick" in t.split() and "fox" in t.split())
    assert q_count(seg, "SELECT COUNT(*) FROM people WHERE TEXT_MATCH(doc, 'dog OR fox')") \
        == sum(1 for t in texts if "dog" in t.split() or "fox" in t.split())
    assert q_count(seg, "SELECT COUNT(*) FROM people WHERE "
                   "TEXT_MATCH(doc, 'dog AND NOT lazy')") \
        == sum(1 for t in texts if "dog" in t.split() and "lazy" not in t.split())


def test_text_match_phrase(jenv):
    seg, _, _, texts, _ = jenv
    sql = 'SELECT COUNT(*) FROM people WHERE TEXT_MATCH(doc, \'"quick brown"\')'
    want = sum(1 for t in texts if "quick brown" in t)
    assert q_count(seg, sql) == want
    # phrase must NOT match "quick red fox" (terms present but not adjacent in other rows)
    sql2 = 'SELECT COUNT(*) FROM people WHERE TEXT_MATCH(doc, \'"quick fox"\')'
    assert q_count(seg, sql2) == 0


def test_text_match_prefix_and_regex(jenv):
    seg, _, _, texts, _ = jenv
    assert q_count(seg, "SELECT COUNT(*) FROM people WHERE TEXT_MATCH(doc, 'qu*')") \
        == sum(1 for t in texts if any(w.startswith("qu") for w in t.split()))
    assert q_count(seg, "SELECT COUNT(*) FROM people WHERE TEXT_MATCH(doc, '/ja.a/')") \
        == sum(1 for t in texts if "java" in t.split())


def test_json_key_with_control_chars_roundtrip(tmp_path):
    """Key-blob encoding is length-delimited: values containing \\x02 etc. must survive."""
    from pinot_tpu.segment.indexes.jsonidx import JsonIndexReader, create_json_index
    docs = ['{"a": "x\\u0002y"}', '{"a": "z"}', '{"b": 1}']
    p = str(tmp_path / "j.npz")
    create_json_index(p, docs)
    idx = JsonIndexReader(p, 3)
    np.testing.assert_array_equal(idx.match('"$.a" = \'z\''), [False, True, False])
    np.testing.assert_array_equal(idx.match('"$.b" = 1'), [False, False, True])


def test_json_match_double_quote_inside_string_literal(tmp_path):
    from pinot_tpu.segment.indexes.jsonidx import json_match_scan
    docs = ['{"a": "say \\"hi\\" ok"}', '{"a": "other"}']
    got = json_match_scan(docs, '"$.a" = \'say "hi" ok\'')
    np.testing.assert_array_equal(got, [True, False])


def test_json_match_mixed_numeric_forms(tmp_path):
    from pinot_tpu.segment.indexes.jsonidx import json_match_scan
    docs = ['{"n": 1}', '{"n": 1.0}', '{"n": 2}']
    np.testing.assert_array_equal(json_match_scan(docs, '"$.n" = 1'), [True, True, False])


def test_text_match_unterminated_quote_is_validation_error(jenv):
    from pinot_tpu.query.context import QueryValidationError
    seg, _, _, _, _ = jenv
    with pytest.raises(QueryValidationError):
        execute_query([seg], "SELECT COUNT(*) FROM people WHERE TEXT_MATCH(doc, '\"oops')")


def test_text_match_bare_not_is_must_not(jenv):
    from pinot_tpu.segment.indexes.text import text_match_scan
    docs = ["apple pie", "banana split", "cherry cake"]
    # Lucene: 'apple NOT banana' == apple AND NOT banana
    np.testing.assert_array_equal(text_match_scan(docs, "apple NOT banana"),
                                  [True, False, False])


def test_json_match_neq_flattened_record_semantics(jenv):
    from pinot_tpu.segment.indexes.jsonidx import json_match_scan
    docs = ['{"arr":[{"x":1},{"x":2}]}', '{"arr":[{"x":3}]}', '{"arr":[{"x":1}]}']
    # per flattened record: doc 0 has a record with x=2 (satisfies <> 1)
    np.testing.assert_array_equal(json_match_scan(docs, '"$.arr[*].x" <> 1'),
                                  [True, True, False])


def test_json_extract_quoted_bracket_key():
    from pinot_tpu.engine.expr import eval_expr
    from pinot_tpu.sql.parser import Parser
    e = Parser("SELECT json_extract_scalar(js, '$.a[''b'']', 'STRING', 'd') FROM t") \
        .parse().select[0][0]
    got = eval_expr(e, {"js": np.asarray(['{"a": {"b": "v"}}'], dtype=object)})
    assert list(got) == ["v"]


def test_json_match_malformed_is_validation_error(jenv):
    from pinot_tpu.query.context import QueryValidationError
    seg, _, _, _, _ = jenv
    with pytest.raises(QueryValidationError):
        execute_query([seg], "SELECT COUNT(*) FROM people WHERE "
                      "JSON_MATCH(js, '''a'' = ''b''')")


def test_json_match_on_mutable_segment(jenv):
    """Mutable readers have no json_index attr -> must fall back to the scan path."""
    from pinot_tpu.schema import DataType, Schema, dimension
    from pinot_tpu.segment.mutable import MutableSegment
    schema = Schema("m", [dimension("js", DataType.JSON)])
    seg = MutableSegment("m__0", schema)
    for i in range(10):
        seg.index({"js": f'{{"a": {i % 3}}}'})
    res = execute_query([seg], "SELECT COUNT(*) FROM m WHERE JSON_MATCH(js, '\"$.a\" = 1')")
    assert int(res.rows[0][0]) == sum(1 for i in range(10) if i % 3 == 1)


def test_text_match_selection(jenv):
    seg, _, _, texts, _ = jenv
    res = execute_query([seg], "SELECT doc FROM people WHERE "
                        "TEXT_MATCH(doc, '\"query engine\"') LIMIT 500")
    assert len(res.rows) == sum(1 for t in texts if "query engine" in t)
    assert all("query engine" in r[0] for r in res.rows)


# -- realtime (mutable) text index -------------------------------------------

class TestMutableTextIndex:
    """Reference: RealtimeLuceneTextIndexReader — TEXT_MATCH over a consuming
    segment rides an incrementally-maintained index, not a per-query rescan."""

    def _mutable(self):
        from pinot_tpu.schema import DataType, Schema, dimension, metric
        from pinot_tpu.segment.mutable import MutableSegment
        schema = Schema("logs", [dimension("msg", DataType.STRING),
                                 metric("n", DataType.INT)])
        seg = MutableSegment("logs__0__0__x", schema,
                             text_index_columns=["msg"])
        for i, msg in enumerate(["connection reset by peer",
                                 "auth failed for user bob",
                                 "connection timeout",
                                 "all good"]):
            seg.index({"msg": msg, "n": i})
        return seg

    def test_index_maintained_and_queryable(self):
        seg = self._mutable()
        idx = seg.column("msg").text_index
        assert idx is not None
        assert idx.match("connection").tolist() == [True, False, True, False]
        assert idx.match('"connection reset"').tolist() == [True, False, False, False]
        assert idx.match("auth AND bob").tolist() == [False, True, False, False]
        assert idx.match("time*").tolist() == [False, False, True, False]

    def test_text_match_query_on_mutable_segment(self):
        from pinot_tpu.query.executor import execute_query
        seg = self._mutable()
        res = execute_query([seg], "SELECT COUNT(*) FROM logs "
                                   "WHERE TEXT_MATCH(msg, 'connection')")
        assert res.rows[0][0] == 2
        res = execute_query([seg], "SELECT SUM(n) FROM logs "
                                   "WHERE TEXT_MATCH(msg, 'NOT connection')")
        assert res.rows[0][0] == 1 + 3

    def test_snapshot_isolation(self):
        seg = self._mutable()
        view = seg.column("msg").text_index
        seg.index({"msg": "connection again", "n": 99})
        # the earlier view must not see the new doc; a fresh view must
        assert len(view.match("connection")) == 4
        assert seg.column("msg").text_index.match("connection").tolist() == [
            True, False, True, False, True]

    def test_unindexed_column_falls_back(self):
        from pinot_tpu.query.executor import execute_query
        from pinot_tpu.schema import DataType, Schema, dimension, metric
        from pinot_tpu.segment.mutable import MutableSegment
        schema = Schema("logs2", [dimension("msg", DataType.STRING),
                                  metric("n", DataType.INT)])
        seg = MutableSegment("x", schema)  # no text index configured
        seg.index({"msg": "hello world", "n": 1})
        seg.index({"msg": "bye", "n": 2})
        assert seg.column("msg").text_index is None
        res = execute_query([seg], "SELECT COUNT(*) FROM logs2 "
                                   "WHERE TEXT_MATCH(msg, 'hello')")
        assert res.rows[0][0] == 1

    def test_consuming_segment_through_cluster(self, tmp_path):
        import json as _json
        from pinot_tpu.cluster import QuickCluster
        from pinot_tpu.ingest.stream import MemoryStream
        from pinot_tpu.schema import DataType, Schema, dimension, metric
        from pinot_tpu.table import IndexingConfig, StreamConfig, TableConfig, TableType
        MemoryStream.reset_all()
        try:
            cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
            schema = Schema("rt_logs", [dimension("msg", DataType.STRING),
                                        metric("n", DataType.INT)])
            cfg = TableConfig(
                "rt_logs", table_type=TableType.REALTIME, replication=1,
                indexing=IndexingConfig(text_index_columns=["msg"]),
                stream=StreamConfig(stream_type="memory", topic="rtl_topic",
                                    decoder="json", flush_threshold_rows=1000))
            cluster.create_realtime_table(schema, cfg, 1)
            stream = MemoryStream.get("rtl_topic")
            for i, m in enumerate(["connection reset", "auth ok", "connection slow"]):
                stream.produce(_json.dumps({"msg": m, "n": i}), partition=0)
            cluster.pump_realtime(cfg.table_name_with_type)
            res = cluster.query("SELECT COUNT(*) FROM rt_logs "
                                "WHERE TEXT_MATCH(msg, 'connection')")
            assert res.rows[0][0] == 2
        finally:
            MemoryStream.reset_all()


def test_text_match_fuzzy(jenv):
    """Lucene fuzzy terms: term~ (2 edits) / term~1 — VERDICT r4 #8.
    Differential against a python Levenshtein oracle over the raw texts,
    on both the indexed and the index-less (scan) paths."""
    seg, seg_noidx, _, texts, _ = jenv

    def lev(a, b):
        if len(a) < len(b):
            a, b = b, a
        prev = list(range(len(b) + 1))
        for i, ca in enumerate(a, 1):
            cur = [i]
            for j, cb in enumerate(b, 1):
                cur.append(min(prev[j] + 1, cur[-1] + 1,
                               prev[j - 1] + (ca != cb)))
            prev = cur
        return prev[-1]

    for q, k in (("fox~1", 1), ("lazyy~", 2), ("quik~1", 1)):
        term = q.split("~")[0]
        want = sum(1 for t in texts
                   if any(lev(term, w) <= k for w in t.split()))
        sql = f"SELECT COUNT(*) FROM people WHERE TEXT_MATCH(doc, '{q}')"
        assert q_count(seg, sql) == want, (q, want)
        assert q_count(seg_noidx, sql) == want, (q, want)
    # fuzzy composes with the boolean algebra
    sql = ("SELECT COUNT(*) FROM people WHERE "
           "TEXT_MATCH(doc, 'fox~1 AND NOT lazy')")
    want = sum(1 for t in texts
               if any(lev("fox", w) <= 1 for w in t.split())
               and "lazy" not in t.split())
    assert q_count(seg, sql) == want
