"""Realtime (consuming-segment) inverted index: incrementally-maintained
postings, consumed by the host executor's index-aware filter path.

Reference: `pinot-segment-local/.../realtime/impl/invertedindex/
RealtimeInvertedIndex.java` + BitmapBasedFilterOperator — selective filters on
consuming segments no longer always scan.
"""

import json

import numpy as np
import pytest

from pinot_tpu.cluster.enclosure import QuickCluster
from pinot_tpu.query.executor import ServerQueryExecutor
from pinot_tpu.schema import DataType, Schema, date_time, dimension, metric
from pinot_tpu.segment.mutable import MutableSegment
from pinot_tpu.segment.writer import SegmentBuilder, SegmentGeneratorConfig
from pinot_tpu.segment.reader import load_segment
from pinot_tpu.table import IndexingConfig, StreamConfig, TableConfig, TableType


def _schema():
    return Schema("ev", [
        dimension("user", DataType.STRING),
        metric("v", DataType.LONG),
        date_time("ts", DataType.LONG),
    ])


def _rows(n, seed=7):
    rng = np.random.default_rng(seed)
    users = rng.choice([f"u{i}" for i in range(20)], n)
    return [{"user": str(users[i]), "v": int(i), "ts": 1_700_000_000_000 + i}
            for i in range(n)]


def test_mutable_inverted_index_postings_track_appends():
    seg = MutableSegment("s", _schema(), inverted_index_columns=["user"])
    rows = _rows(200)
    for r in rows[:120]:
        seg.index(r)
    reader = seg.column("user")
    inv = reader.inverted_index
    assert inv is not None  # mutable.py no longer pins inverted_index = None
    d = reader.dictionary
    for dict_id in range(len(d)):
        want = [i for i, r in enumerate(rows[:120]) if r["user"] == d.get(dict_id)]
        assert inv.doc_ids_for(dict_id).tolist() == want
    # growth: new snapshot sees new docs, ids stay consistent with ITS dictionary
    for r in rows[120:]:
        seg.index(r)
    inv2 = seg.column("user").inverted_index
    d2 = seg.column("user").dictionary
    for dict_id in range(len(d2)):
        want = [i for i, r in enumerate(rows) if r["user"] == d2.get(dict_id)]
        assert inv2.doc_ids_for(dict_id).tolist() == want


def test_consuming_vs_committed_parity(tmp_path):
    """Same data, same query: consuming segment (realtime inverted index) and
    the committed immutable segment (CSR inverted index) agree exactly."""
    schema = _schema()
    rows = _rows(500)
    mutable = MutableSegment("s", schema, inverted_index_columns=["user"])
    for r in rows:
        mutable.index(r)
    cols = {"user": [r["user"] for r in rows],
            "v": np.array([r["v"] for r in rows]),
            "ts": np.array([r["ts"] for r in rows])}
    committed = load_segment(SegmentBuilder(
        schema, SegmentGeneratorConfig(inverted_index_columns=["user"])
    ).build(cols, str(tmp_path), "s0"))
    assert committed.column("user").inverted_index is not None

    ex = ServerQueryExecutor()
    for sql in ("SELECT COUNT(*), SUM(v) FROM ev WHERE user = 'u3'",
                "SELECT COUNT(*) FROM ev WHERE user IN ('u1', 'u7', 'u19')",
                "SELECT user, COUNT(*) FROM ev WHERE user IN ('u2','u4') "
                "GROUP BY user ORDER BY user LIMIT 10"):
        a = ex.execute([mutable], sql)
        b = ex.execute([committed], sql)
        assert a.rows == b.rows, sql


def test_index_aware_path_correct_mid_growth():
    """Query, grow, query again: each snapshot's postings are trimmed to its
    own row count — no phantom rows from the writer racing the reader."""
    seg = MutableSegment("s", _schema(), inverted_index_columns=["user"])
    rows = _rows(300, seed=11)
    ex = ServerQueryExecutor()
    prev = 0
    for cut in (50, 180, 300):
        for r in rows[prev:cut]:
            seg.index(r)
        prev = cut
        got = ex.execute([seg], "SELECT COUNT(*) FROM ev WHERE user = 'u5'")
        want = sum(1 for r in rows[:cut] if r["user"] == "u5")
        assert got.rows[0][0] == want, cut


def test_realtime_table_uses_inverted_index_end_to_end(tmp_path):
    """Cluster path: indexing.invertedIndexColumns on a realtime table flows
    into the consuming segment, selective filters answer correctly from it."""
    from pinot_tpu.ingest.stream import MemoryStream
    schema = _schema()
    MemoryStream.create("ev_topic", 1)
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    cfg = TableConfig(
        "ev", table_type=TableType.REALTIME, time_column="ts",
        stream=StreamConfig(topic="ev_topic", flush_threshold_rows=10_000),
        indexing=IndexingConfig(inverted_index_columns=["user"]))
    cluster.controller.add_schema(schema)
    cluster.controller.add_realtime_table(cfg, num_partitions=1)
    topic = MemoryStream.get("ev_topic")
    rows = _rows(250, seed=13)
    for r in rows:
        topic.produce(json.dumps(r), partition=0)
    cluster.pump_realtime(cfg.table_name_with_type)

    # the segment is still CONSUMING (threshold 10k) — the filter below runs
    # against the mutable segment's realtime inverted index
    node = cluster.servers[0]
    rt = node._realtime_managers[cfg.table_name_with_type]
    handler = next(iter(rt.consumers.values()))
    assert handler.mutable.column("user").inverted_index is not None

    res = cluster.query("SELECT COUNT(*), SUM(v) FROM ev WHERE user = 'u9'")
    want = [r for r in rows if r["user"] == "u9"]
    assert res.rows[0][0] == len(want)
    assert res.rows[0][1] == sum(r["v"] for r in want)
