"""Lead-controller election + failover tests.

Reference pattern: LeadControllerManager tests — one leader at a time, standby
takeover on lease expiry, deposed leader steps down, metadata survives.
"""

import numpy as np
import pytest

from pinot_tpu.cluster.catalog import Catalog
from pinot_tpu.cluster.controller import Controller
from pinot_tpu.cluster.deepstore import LocalDeepStore
from pinot_tpu.cluster.leadership import (ControllerFailover, LeaderElection)
from pinot_tpu.schema import Schema, dimension, metric
from pinot_tpu.table import TableConfig


@pytest.fixture()
def ds(tmp_path):
    return LocalDeepStore(str(tmp_path / "deepstore"))


def test_single_winner(ds):
    a = LeaderElection(ds, "ctrl_a", lease_ttl_s=5.0, settle_s=0.0)
    b = LeaderElection(ds, "ctrl_b", lease_ttl_s=5.0, settle_s=0.0)
    assert a.try_acquire()
    assert not b.try_acquire()      # live lease blocks the standby
    assert a.renew()
    assert a.is_leader and not b.is_leader


def test_takeover_after_expiry(ds):
    a = LeaderElection(ds, "ctrl_a", lease_ttl_s=0.05, settle_s=0.0)
    b = LeaderElection(ds, "ctrl_b", lease_ttl_s=5.0, settle_s=0.0)
    assert a.try_acquire()
    import time
    time.sleep(0.1)                 # leader "crashes": never renews
    assert b.try_acquire()
    assert b.epoch == a.epoch + 1   # epoch fences the old incarnation
    # the deposed leader notices at its next renewal and steps down
    assert not a.renew()
    assert not a.is_leader


def test_voluntary_release(ds):
    a = LeaderElection(ds, "ctrl_a", lease_ttl_s=60.0, settle_s=0.0)
    b = LeaderElection(ds, "ctrl_b", lease_ttl_s=5.0, settle_s=0.0)
    assert a.try_acquire()
    a.release()
    assert b.try_acquire()          # no TTL wait after a clean step-down


def test_failover_restores_catalog(tmp_path, ds):
    """Standby controller takes over with the leader's metadata intact."""
    schema = Schema("trips", [dimension("city"), metric("fare")])

    leader = Controller("ctrl_a", Catalog(), ds, str(tmp_path / "a"))
    fo_a = ControllerFailover(
        leader, LeaderElection(ds, "ctrl_a", lease_ttl_s=0.05, settle_s=0.0))
    assert fo_a.lead()

    # leader does real work: schema + table land in the checkpoint
    leader.add_schema(schema)
    leader.add_table(TableConfig("trips", replication=2))
    assert fo_a.heartbeat()

    # leader dies (stops renewing); standby polls, wins, restores
    import time
    time.sleep(0.1)
    standby = Controller("ctrl_b", Catalog(), ds, str(tmp_path / "b"))
    fo_b = ControllerFailover(
        standby, LeaderElection(ds, "ctrl_b", lease_ttl_s=5.0, settle_s=0.0))
    assert fo_b.try_takeover()
    assert "trips_OFFLINE" in standby.catalog.table_configs
    assert standby.catalog.table_configs["trips_OFFLINE"].replication == 2
    assert standby.catalog.schemas["trips"].has_column("fare")

    # the old leader's next heartbeat detects deposition
    assert not fo_a.heartbeat()
    assert not fo_a.election.is_leader

    # the new leader keeps checkpointing: further writes survive ANOTHER failover
    standby.add_schema(Schema("orders", [dimension("id")]))
    standby.add_table(TableConfig("orders"))
    time.sleep(0.01)
    third = Controller("ctrl_c", Catalog(), ds, str(tmp_path / "c"))
    fo_b.election.release()
    fo_c = ControllerFailover(
        third, LeaderElection(ds, "ctrl_c", lease_ttl_s=5.0, settle_s=0.0))
    assert fo_c.try_takeover()
    assert "orders_OFFLINE" in third.catalog.table_configs


def test_stale_release_does_not_clobber_successor(ds):
    """An ex-leader's release() after being deposed must not expire the NEW
    leader's lease (split-brain prevention)."""
    import time
    a = LeaderElection(ds, "ctrl_a", lease_ttl_s=0.05, settle_s=0.0)
    b = LeaderElection(ds, "ctrl_b", lease_ttl_s=60.0, settle_s=0.0)
    assert a.try_acquire()
    time.sleep(0.1)
    assert b.try_acquire()
    a.release()                      # stale: A still thinks it leads
    assert b.renew(), "successor's lease must survive a stale release"


def test_restarted_same_id_bumps_epoch(ds):
    """A replacement process reusing the instance id gets a NEW epoch, so the
    hung original incarnation is fenced out at its next renew."""
    import time
    original = LeaderElection(ds, "ctrl_a", lease_ttl_s=0.05, settle_s=0.0)
    assert original.try_acquire()
    time.sleep(0.1)                  # original hangs past expiry
    replacement = LeaderElection(ds, "ctrl_a", lease_ttl_s=60.0, settle_s=0.0)
    assert replacement.try_acquire()
    assert replacement.epoch == original.epoch + 1
    assert not original.renew(), "hung incarnation must be fenced"


def test_deposed_leader_cannot_overwrite_checkpoint(tmp_path, ds):
    """Late catalog events on a deposed leader must not clobber the successor's
    checkpoint (the checkpoint is epoch-fenced like the lease)."""
    import time
    a = Controller("ctrl_a", Catalog(), ds, str(tmp_path / "a"))
    fo_a = ControllerFailover(
        a, LeaderElection(ds, "ctrl_a", lease_ttl_s=0.05, settle_s=0.0))
    assert fo_a.lead()
    a.add_schema(Schema("t1", [dimension("x")]))
    time.sleep(0.1)                  # A's lease expires

    b = Controller("ctrl_b", Catalog(), ds, str(tmp_path / "b"))
    fo_b = ControllerFailover(
        b, LeaderElection(ds, "ctrl_b", lease_ttl_s=60.0, settle_s=0.0))
    assert fo_b.try_takeover()
    b.add_schema(Schema("t2", [dimension("y")]))   # successor's new state

    # deposed A fires a late catalog event; the fenced checkpoint must refuse
    a.add_schema(Schema("stale", [dimension("z")]))
    c = Controller("ctrl_c", Catalog(), ds, str(tmp_path / "c"))
    fo_b.election.release()
    fo_c = ControllerFailover(
        c, LeaderElection(ds, "ctrl_c", lease_ttl_s=60.0, settle_s=0.0))
    assert fo_c.try_takeover()
    assert "t2" in c.catalog.schemas, "successor's writes must survive"
    assert "stale" not in c.catalog.schemas, "deposed leader's write leaked"


def test_standby_does_not_takeover_live_leader(tmp_path, ds):
    leader = Controller("ctrl_a", Catalog(), ds, str(tmp_path / "a"))
    fo_a = ControllerFailover(
        leader, LeaderElection(ds, "ctrl_a", lease_ttl_s=60.0, settle_s=0.0))
    assert fo_a.lead()
    standby = Controller("ctrl_b", Catalog(), ds, str(tmp_path / "b"))
    fo_b = ControllerFailover(
        standby, LeaderElection(ds, "ctrl_b", lease_ttl_s=5.0, settle_s=0.0))
    assert not fo_b.try_takeover()
    assert fo_a.heartbeat()
