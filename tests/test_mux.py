"""Mux transport tests: differential vs legacy, concurrency fuzz, zero-copy.

Three proofs the multiplexed data plane (`cluster/mux.py`) must carry:

* the mux and legacy transports are OBSERVABLY IDENTICAL — result bytes,
  stats key sets, EXPLAIN ANALYZE plans, and server span trees all match
  (reference analog: QueryRoutingTest asserting Netty and in-proc dispatch
  agree on DataTable contents);
* tagged responses on one shared connection always land on the right
  request under heavy interleaving, and a mid-stream disconnect fails ONLY
  the in-flight tags before the pool recovers on the next submit;
* a 1M-element array payload is decoded with zero copies
  (`np.shares_memory` against the receive buffer).
"""

import json
import socket
import threading
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from pinot_tpu.cluster.broker import Broker
from pinot_tpu.cluster.catalog import Catalog
from pinot_tpu.cluster.controller import Controller
from pinot_tpu.cluster.deepstore import LocalDeepStore
from pinot_tpu.cluster.http_service import HttpService
from pinot_tpu.cluster.mux import MuxClient, serve_mux_stream
from pinot_tpu.cluster.process import BrokerClient, ControllerClient
from pinot_tpu.cluster.remote import ControllerDeepStore, RemoteCatalog
from pinot_tpu.cluster.server import ServerNode
from pinot_tpu.cluster.services import (BrokerService, ControllerService,
                                        ServerService)
from pinot_tpu.cluster.wire import (decode_segment_result, decode_value,
                                    encode_segment_result_parts, encode_value)
from pinot_tpu.query.reduce import DensePartial, SegmentResult
from pinot_tpu.schema import DataType, FieldSpec, Schema
from pinot_tpu.segment.writer import SegmentBuilder, SegmentGeneratorConfig
from pinot_tpu.table import TableConfig


def _wait_until(fn, timeout=15.0):
    from conftest import wait_until
    return wait_until(fn, timeout=timeout, interval=0.05, swallow=())


# -- differential: mux vs legacy over a real HTTP cluster --------------------

SCHEMA = Schema("trips", [
    FieldSpec("city", DataType.STRING),
    FieldSpec("fare", DataType.DOUBLE),
    FieldSpec("n", DataType.INT),
])

#: transport-mechanics spans excluded when diffing server execution trees —
#: the wire decomposition differs BY DESIGN between the two transports
#: (matches the exclusion set in test_tracing's dual-transport differential)
WIRE_SPANS = frozenset(("serialize", "send", "deserialize", "queue_wait",
                        "mux:frame_queue", "mux:flow_control"))


@pytest.fixture
def dual_broker_cluster(tmp_path):
    """Controller + 2 servers + TWO brokers over HTTP: one pinned to the mux
    transport, one pinned to legacy one-exchange-per-query POST /query."""
    catalog = Catalog()
    deepstore = LocalDeepStore(str(tmp_path / "deepstore"))
    controller = Controller("controller_0", catalog, deepstore,
                            str(tmp_path / "ctrl"))
    csvc = ControllerService(controller)
    services = [csvc]
    catalogs = []
    servers = []
    try:
        for i in range(2):
            rc = RemoteCatalog(csvc.url, poll_timeout_s=1.0)
            catalogs.append(rc)
            node = ServerNode(f"server_{i}", rc, ControllerDeepStore(csvc.url),
                              str(tmp_path / f"server_{i}"))
            ssvc = ServerService(node)
            services.append(ssvc)
            servers.append((node, rc, ssvc))
        bsvcs = {}
        for name, mux in (("mux", True), ("legacy", False)):
            rc = RemoteCatalog(csvc.url, poll_timeout_s=1.0)
            catalogs.append(rc)
            bsvc = BrokerService(Broker(f"broker_{name}", rc), mux=mux)
            services.append(bsvc)
            bsvcs[name] = bsvc
        yield {"csvc": csvc, "servers": servers, "bsvcs": bsvcs,
               "tmp": tmp_path}
    finally:
        for rc in catalogs:
            rc.close()
        for s in services:
            s.stop()


def _load_trips(cluster):
    c = ControllerClient(cluster["csvc"].url)
    c.add_schema(SCHEMA)
    cfg = TableConfig("trips", replication=2)
    c.add_table(cfg)
    builder = SegmentBuilder(SCHEMA, SegmentGeneratorConfig())
    seg1 = builder.build(
        {"city": np.array(["nyc", "sf", "nyc", "la"], dtype=object),
         "fare": np.array([10.0, 20.0, 30.0, 7.5], dtype=np.float64),
         "n": np.array([1, 2, 3, 4], dtype=np.int32)},
        str(cluster["tmp"] / "b1"), "trips_0")
    seg2 = builder.build(
        {"city": np.array(["sf", "la", "nyc"], dtype=object),
         "fare": np.array([5.0, 7.0, 2.5], dtype=np.float64),
         "n": np.array([5, 6, 7], dtype=np.int32)},
        str(cluster["tmp"] / "b2"), "trips_1")
    c.upload_segment(cfg.table_name_with_type, seg1)
    c.upload_segment(cfg.table_name_with_type, seg2)
    assert _wait_until(lambda: all(
        len(node.segments_served(cfg.table_name_with_type)) == 2
        for node, _, _ in cluster["servers"]))


def _converged_clients(cluster):
    """Both broker mirrors answering the full-table count: ready to diff."""
    clients = {name: BrokerClient(svc.url)
               for name, svc in cluster["bsvcs"].items()}

    def ready(bc):
        try:
            return bc.query("SELECT COUNT(*) FROM trips"
                            )["resultTable"]["rows"][0][0] == 7
        except Exception:
            return None
    for bc in clients.values():
        assert _wait_until(lambda: ready(bc))
    return clients


def test_mux_vs_legacy_differential(dual_broker_cluster):
    """The two transports return byte-identical result tables, identical
    stats key sets, and matching deterministic counters."""
    _load_trips(dual_broker_cluster)
    clients = _converged_clients(dual_broker_cluster)

    queries = [
        "SELECT city, SUM(fare) AS total FROM trips "
        "GROUP BY city ORDER BY total DESC",
        "SELECT COUNT(*), MIN(n), MAX(fare) FROM trips WHERE fare > 6",
        "SELECT city, fare, n FROM trips WHERE n >= 2 ORDER BY n LIMIT 10",
        "SELECT DISTINCT city FROM trips ORDER BY city",
    ]
    deterministic = ("numDocsScanned", "numSegmentsQueried",
                     "numSegmentsProcessed", "numServersQueried",
                     "numServersResponded", "partialResult",
                     "numEntriesScannedInFilter")
    for sql in queries:
        resp_m = clients["mux"].query(sql)
        resp_l = clients["legacy"].query(sql)
        # byte-identical results
        assert (json.dumps(resp_m["resultTable"], sort_keys=True) ==
                json.dumps(resp_l["resultTable"], sort_keys=True)), sql
        # identical stats surfaces: COUNTER_KEYS zero-fill means the mux-only
        # counters (muxFrameQueueMs/muxFlowControlMs) exist on BOTH sides
        assert set(resp_m) == set(resp_l), sql
        assert "muxFrameQueueMs" in resp_m and "muxFlowControlMs" in resp_m
        for k in deterministic:
            if k in resp_m:
                assert resp_m[k] == resp_l[k], (sql, k)


def test_mux_vs_legacy_explain_analyze(dual_broker_cluster):
    """EXPLAIN ANALYZE through both transports: identical operator trees and
    row counts (the Ms column is wall clock and excluded by design)."""
    _load_trips(dual_broker_cluster)
    clients = _converged_clients(dual_broker_cluster)
    sql = ("EXPLAIN ANALYZE SELECT city, SUM(fare) AS total FROM trips "
           "GROUP BY city ORDER BY total DESC")
    resp_m = clients["mux"].query(sql)
    resp_l = clients["legacy"].query(sql)
    assert (resp_m["resultTable"]["dataSchema"] ==
            resp_l["resultTable"]["dataSchema"])

    def shape(resp):   # [label, id, parent, rows] — drop the Ms column
        return [row[:4] for row in resp["resultTable"]["rows"]]
    assert shape(resp_m) == shape(resp_l)
    assert set(resp_m) == set(resp_l)
    assert resp_m["analyze"] is True


def test_mux_vs_legacy_trace_span_tree(dual_broker_cluster):
    """OPTION(trace=true): the server execution span tree (everything that is
    not wire mechanics) is identical across transports, and each transport
    exposes exactly its own wire spans."""
    _load_trips(dual_broker_cluster)
    clients = _converged_clients(dual_broker_cluster)
    sql = ("SELECT city, SUM(fare) AS total FROM trips GROUP BY city "
           "ORDER BY total DESC OPTION(trace=true)")
    names_m = [s["name"] for s in clients["mux"].query(sql)["traceInfo"]]
    names_l = [s["name"] for s in clients["legacy"].query(sql)["traceInfo"]]

    def exec_tree(names):
        return set(n for n in names
                   if n.rsplit("/", 1)[-1] not in WIRE_SPANS)
    assert exec_tree(names_m) == exec_tree(names_l)
    # both carry the spliced per-server segment spans
    for names in (names_m, names_l):
        assert any(n.startswith("server:server_") and "/segment:" in n
                   for n in names)
    # the mux wire decomposition only appears on the mux transport
    assert "mux:frame_queue" in names_m
    assert "mux:frame_queue" not in names_l


# -- concurrency fuzz against a raw mux stream -------------------------------

@pytest.fixture
def echo_mux():
    """A bare /mux endpoint whose execute echoes the request's value back as
    `num_docs_scanned` — any tag mismatch becomes a visible wrong answer.
    Requests with `hold` block until the gate opens (in-flight on the wire)."""
    pool = ThreadPoolExecutor(max_workers=8, thread_name_prefix="mux-echo")
    gate = threading.Event()
    gate.set()

    def execute(payload, flow_wait_ms):
        d = json.loads(bytes(payload).decode())
        if d.get("hold"):
            gate.wait(timeout=30.0)
        r = SegmentResult("groups")
        r.num_docs_scanned = d["v"]
        return 200, encode_segment_result_parts(r)

    svc = HttpService()
    svc.route("POST", "mux", lambda parts, params, body:
              (200, "application/octet-stream",
               serve_mux_stream(body, execute, executor=pool,
                                max_inflight=32)),
              duplex=True)
    svc.start()
    try:
        yield {"svc": svc, "gate": gate}
    finally:
        gate.set()
        svc.stop()
        pool.shutdown(wait=False)


def _payload(v, hold=False):
    return json.dumps({"v": v, **({"hold": True} if hold else {})}).encode()


def test_mux_concurrent_tag_matching(echo_mux):
    """8 threads x 25 interleaved queries over ONE connection: every response
    lands on the future whose tag requested it."""
    mc = MuxClient(echo_mux["svc"].url, streams=1, timeout_s=30.0)
    try:
        mismatches = []

        def worker(t):
            futs = [(t * 1000 + j, mc.submit(_payload(t * 1000 + j)))
                    for j in range(25)]
            for want, fut in futs:
                got = fut.result(timeout=30.0).num_docs_scanned
                if got != want:
                    mismatches.append((want, got))
        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(8)]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=60.0)
        assert not any(th.is_alive() for th in threads)
        assert mismatches == []
    finally:
        mc.close()


def test_mux_out_of_order_completion(echo_mux):
    """Responses are matched by tag, not arrival order: fast queries complete
    while earlier held queries are still in flight on the same stream."""
    gate = echo_mux["gate"]
    mc = MuxClient(echo_mux["svc"].url, streams=1, timeout_s=30.0)
    try:
        gate.clear()
        held = [mc.submit(_payload(100 + i, hold=True)) for i in range(3)]
        fast = [mc.submit(_payload(200 + i)) for i in range(3)]
        for i, fut in enumerate(fast):
            assert fut.result(timeout=15.0).num_docs_scanned == 200 + i
        assert not any(f.done() for f in held)
        gate.set()
        for i, fut in enumerate(held):
            assert fut.result(timeout=15.0).num_docs_scanned == 100 + i
    finally:
        gate.set()
        mc.close()


def test_mux_disconnect_fails_inflight_then_recovers(echo_mux):
    """A mid-stream disconnect fails exactly the in-flight tags with
    ConnectionError (what `_is_transport_failure` expects of a dead server);
    the next submit reconnects and the stream works again."""
    from pinot_tpu.utils.metrics import get_registry
    gate = echo_mux["gate"]
    mc = MuxClient(echo_mux["svc"].url, streams=1, timeout_s=30.0)
    try:
        # a completed exchange on the same stream first
        assert mc.submit(_payload(7)).result(timeout=15.0) \
            .num_docs_scanned == 7

        gate.clear()
        held = [mc.submit(_payload(100 + i, hold=True)) for i in range(4)]
        conn = mc._slots[0]
        assert _wait_until(lambda: len(conn._pending) == 4)

        reconnects = get_registry().counter_value(
            "pinot_broker_mux_reconnects")
        conn._conn.sock.shutdown(socket.SHUT_RDWR)  # sever mid-stream
        for fut in held:
            with pytest.raises(ConnectionError):
                fut.result(timeout=15.0)
        assert _wait_until(lambda: conn.closed)
        gate.set()  # release the server-side executions into the dead stream

        # the pool recovers: the next submit opens a fresh stream
        assert mc.submit(_payload(42)).result(timeout=15.0) \
            .num_docs_scanned == 42
        assert get_registry().counter_value(
            "pinot_broker_mux_reconnects") == reconnects + 1
    finally:
        gate.set()
        mc.close()


# -- zero-copy decode ---------------------------------------------------------

def test_zero_copy_decode_1m_elements():
    """A 1M-element float64 payload decodes as a VIEW over the receive
    buffer — no copy anywhere between the socket read and the ndarray."""
    arr = np.arange(1_000_000, dtype=np.float64)
    buf = encode_value(arr)
    out = decode_value(memoryview(buf))
    assert isinstance(out, np.ndarray)
    assert out.dtype == np.float64 and out.shape == (1_000_000,)
    assert np.array_equal(out, arr)
    assert np.shares_memory(out, np.frombuffer(buf, dtype=np.uint8))


def test_zero_copy_dense_partial_response():
    """The full response path a mux frame carries: a dense group-by partial
    is encoded as gathered parts and decoded as views over the joined frame
    body — counts and every aggregate column share the frame's memory."""
    keys = 1_000_000
    dp = DensePartial(token=("k", (keys,), ("h",), keys), cards=(keys,),
                      strides=(1,), num_keys_real=keys,
                      counts=np.ones(keys, dtype=np.int64),
                      outs={"0.sum": np.arange(keys, dtype=np.float64)},
                      group_values=[np.arange(keys, dtype=np.int64)])
    r = SegmentResult("groups", dense=dp)
    frame = b"".join(bytes(p) for p in encode_segment_result_parts(r))
    decoded = decode_segment_result(memoryview(frame))
    base = np.frombuffer(frame, dtype=np.uint8)
    got = decoded.dense
    assert got is not None and got.num_keys_real == keys
    assert np.array_equal(got.outs["0.sum"], dp.outs["0.sum"])
    for payload in (got.counts, got.outs["0.sum"]):
        assert np.shares_memory(payload, base)
