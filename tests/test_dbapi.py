"""DB-API 2.0 driver tests (reference: pinot-jdbc-client PinotDriver /
PinotPreparedStatement over the java-client)."""

import numpy as np
import pytest

import pinot_tpu.dbapi as dbapi
from pinot_tpu.dbapi import ProgrammingError, _substitute, escape
from pinot_tpu.schema import Schema, dimension, metric
from pinot_tpu.table import TableConfig


# -- parameter substitution (pure) -------------------------------------------

def test_escape_literals():
    assert escape(None) == "NULL"
    assert escape(True) == "true"
    assert escape(5) == "5"
    assert escape(2.5) == "2.5"
    assert escape("o'hare") == "'o''hare'"
    assert escape([1, 2, 3]) == "1, 2, 3"


def test_substitute_skips_string_literals():
    sql = _substitute("SELECT * FROM t WHERE a = '?' AND b = ?", [7])
    assert sql == "SELECT * FROM t WHERE a = '?' AND b = 7"
    sql = _substitute("SELECT * FROM t WHERE a = 'it''s ?' AND b = ?", ["x"])
    assert sql == "SELECT * FROM t WHERE a = 'it''s ?' AND b = 'x'"


def test_substitute_count_mismatch():
    with pytest.raises(ProgrammingError):
        _substitute("SELECT ? FROM t", [])
    with pytest.raises(ProgrammingError):
        _substitute("SELECT 1 FROM t", [1])


def test_module_globals():
    assert dbapi.apilevel == "2.0"
    assert dbapi.paramstyle == "qmark"
    assert issubclass(dbapi.ProgrammingError, dbapi.DatabaseError)
    assert issubclass(dbapi.DatabaseError, dbapi.Error)


# -- end-to-end over HTTP ----------------------------------------------------

@pytest.fixture()
def stack(tmp_path):
    from pinot_tpu.cluster.broker import Broker
    from pinot_tpu.cluster.catalog import Catalog
    from pinot_tpu.cluster.controller import Controller
    from pinot_tpu.cluster.deepstore import LocalDeepStore
    from pinot_tpu.cluster.remote import ControllerDeepStore, RemoteCatalog
    from pinot_tpu.cluster.server import ServerNode
    from pinot_tpu.cluster.services import (BrokerService, ControllerService,
                                            ServerService)
    from pinot_tpu.segment.writer import SegmentBuilder
    from conftest import wait_until

    catalog = Catalog()
    ctrl = Controller("c0", catalog, LocalDeepStore(str(tmp_path / "ds")),
                      str(tmp_path / "c"))
    csvc = ControllerService(ctrl)
    cats = [RemoteCatalog(csvc.url, poll_timeout_s=1.0)]
    node = ServerNode("server_0", cats[0], ControllerDeepStore(csvc.url),
                      str(tmp_path / "s0"))
    ssvc = ServerService(node)
    cats.append(RemoteCatalog(csvc.url, poll_timeout_s=1.0))
    bsvc = BrokerService(Broker("b0", cats[1]))

    schema = Schema("trips", [dimension("city"), metric("fare")])
    ctrl.add_schema(schema)
    ctrl.add_table(TableConfig("trips"))
    seg = SegmentBuilder(schema).build(
        {"city": ["nyc", "sf", "nyc", "la"],
         "fare": np.array([1.0, 2.0, 3.0, 4.0])}, str(tmp_path / "b"), "trips_0")
    ctrl.upload_segment("trips_OFFLINE", seg)
    conn = dbapi.connect(bsvc.url)
    try:
        wait_until(lambda: conn.cursor().execute(
            "SELECT COUNT(*) FROM trips").fetchone()[0] == 4)
        yield conn
    finally:
        conn.close()
        for c in cats:
            c.close()
        for s in (csvc, ssvc, bsvc):
            s.stop()


def test_cursor_fetch_and_description(stack):
    cur = stack.cursor()
    cur.execute("SELECT city, SUM(fare) FROM trips GROUP BY city "
                "ORDER BY city LIMIT 10")
    assert [d[0] for d in cur.description] == ["city", "sum(fare)"]
    assert cur.description[0][1] == dbapi.STRING
    assert cur.description[1][1] == dbapi.NUMBER
    assert cur.rowcount == 3
    assert cur.fetchone() == ["la", 4.0]
    assert cur.fetchmany(1) == [["nyc", 4.0]]
    assert cur.fetchall() == [["sf", 2.0]]
    assert cur.fetchone() is None


def test_parameterized_query(stack):
    cur = stack.cursor()
    cur.execute("SELECT COUNT(*) FROM trips WHERE city = ? AND fare >= ?",
                ["nyc", 1.5])
    assert cur.fetchone() == [1]


def test_iteration_and_context_manager(stack):
    with stack.cursor() as cur:
        rows = list(cur.execute("SELECT city FROM trips ORDER BY city LIMIT 10"))
        assert rows == [["la"], ["nyc"], ["nyc"], ["sf"]]
    with pytest.raises(dbapi.InterfaceError):
        cur.fetchone()


def test_fetch_before_execute_raises(stack):
    with pytest.raises(ProgrammingError):
        stack.cursor().fetchall()


def test_bad_sql_raises_operational(stack):
    with pytest.raises(dbapi.OperationalError):
        stack.cursor().execute("SELECT bogus_col FROM trips")


def test_rollback_not_supported(stack):
    stack.commit()  # no-op
    with pytest.raises(dbapi.NotSupportedError):
        stack.rollback()


def test_substitute_skips_comments_and_quoted_identifiers():
    # a ? inside a -- line comment is not a placeholder
    sql = _substitute("SELECT ? FROM t -- what? a comment\nWHERE b = ?", [1, 2])
    assert sql == "SELECT 1 FROM t -- what? a comment\nWHERE b = 2"
    # comment at end of string (no trailing newline)
    sql = _substitute("SELECT ? FROM t -- tail?", [3])
    assert sql == "SELECT 3 FROM t -- tail?"
    # a ? inside a double-quoted identifier is not a placeholder
    sql = _substitute('SELECT "col?name" FROM t WHERE a = ?', [4])
    assert sql == 'SELECT "col?name" FROM t WHERE a = 4'
    # doubled "" escape inside an identifier
    sql = _substitute('SELECT "we""ird?" FROM t WHERE a = ?', [5])
    assert sql == 'SELECT "we""ird?" FROM t WHERE a = 5'


def test_substitute_skips_block_comments():
    sql = _substitute("SELECT /* what? */ a FROM t WHERE b = ?", [1])
    assert sql == "SELECT /* what? */ a FROM t WHERE b = 1"
    # unterminated block comment swallows the rest
    sql = _substitute("SELECT a FROM t /* trailing?", [])
    assert sql == "SELECT a FROM t /* trailing?"
