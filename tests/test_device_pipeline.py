"""Deterministic DeviceQueryPipeline batching tests (no real device work).

A fake mesh executor with controllable latency and full call recording
proves the pipeline's scheduling contract WITHOUT racing on real kernel
times: (a) concurrent submissions coalesce into ONE host fetch, (b) a
timed-out caller's future is never dispatched or fetched, (c) shape-keyed
reuse launches ONE executable for N same-shape queries (stacked) and
collapses byte-identical queries to one dispatch (dedupe). A final smoke
test runs the REAL executor end-to-end on the CPU mesh and asserts
meanBatch > 1, so served-path batching can never silently regress to
one-query-per-round-trip.

Reference: QueryScheduler.java:56 bounds per-server concurrency; here the
pipeline converts that concurrency into batched device round trips.
"""

import threading
import time

import numpy as np
import pytest

from pinot_tpu.cluster import QuickCluster
from pinot_tpu.cluster.device_server import (DEVICE_FALLBACK,
                                             DeviceQueryPipeline, _Item)
from pinot_tpu.table import TableConfig

from conftest import make_ssb_columns


class FakePrepared:
    """Duck-typed PreparedDispatch: only the fields the pipeline reads."""

    def __init__(self, shape, literal, decoded):
        self.kind = "agg"
        self.stackable = True
        self.stack_key = ("shape", shape)
        self.dedupe_key = ("shape", shape, literal)
        self.decode = lambda outs, d=decoded: (d, outs)


class FakeMeshExec:
    """Prepared-API fake: ctx is a dict {shape, literal, fallback?}."""

    def __init__(self, fetch_latency: float = 0.0):
        self.fetch_latency = fetch_latency
        self.prepared = []        # ctxs that reached prepare_partial
        self.launched_keys = []   # one stack_key per kernel launch
        self.fetch_calls = []     # number of trees per fetch() call
        self.fetch_started = threading.Event()

    def prepare_partial(self, ctx, segments):
        self.prepared.append(ctx)
        if ctx.get("fallback"):
            return None
        return FakePrepared(ctx["shape"], ctx["literal"],
                            ("res", ctx["shape"], ctx["literal"]))

    def dispatch_prepared(self, reps):
        groups = {}
        order = []
        for i, p in enumerate(reps):
            key = p.stack_key if p.stackable else ("solo", i)
            if key not in groups:
                groups[key] = []
                order.append(key)
            groups[key].append(i)
        launches = []
        for key in order:
            idxs = groups[key]
            self.launched_keys.append(key)
            outs_dev = {"launch": len(self.launched_keys), "n": len(idxs)}
            launches.append((outs_dev,
                             lambda host, n=len(idxs): [host] * n, idxs))
        return launches

    def fetch(self, trees):
        self.fetch_started.set()
        if self.fetch_latency:
            time.sleep(self.fetch_latency)
        self.fetch_calls.append(len(trees))
        return trees


def _submit_concurrently(pipeline, ctxs):
    """Queue every ctx from its own thread against a NOT-started pipeline,
    wait until all are queued, then start — one deterministic drain."""
    results = [None] * len(ctxs)

    def run(i):
        results[i] = pipeline.execute_partial(ctxs[i], [])

    threads = [threading.Thread(target=run, args=(i,))
               for i in range(len(ctxs))]
    for t in threads:
        t.start()
    deadline = time.time() + 5
    while pipeline._q.qsize() < len(ctxs) and time.time() < deadline:
        time.sleep(0.005)
    assert pipeline._q.qsize() == len(ctxs)
    pipeline.start()
    for t in threads:
        t.join(timeout=10)
    return results


def test_concurrent_submissions_coalesce_into_one_fetch():
    fake = FakeMeshExec()
    pipeline = DeviceQueryPipeline(mesh_exec=fake, start=False)
    try:
        ctxs = [{"shape": "A", "literal": i} for i in range(6)]
        results = _submit_concurrently(pipeline, ctxs)
        assert results == [(("res", "A", i), {"launch": 1, "n": 6})
                           for i in range(6)]
        # six queries, one drain, ONE host fetch for the whole batch
        assert len(fake.fetch_calls) == 1
        assert pipeline.batches == 1
        assert pipeline.stats()["meanBatch"] == 6.0
    finally:
        pipeline.stop()


def test_timed_out_future_not_dispatched_or_fetched():
    fake = FakeMeshExec()
    pipeline = DeviceQueryPipeline(mesh_exec=fake, start=False)
    try:
        stale = _Item({"shape": "A", "literal": 0}, [])
        stale.future.cancel()  # caller timed out while still queued
        live = _Item({"shape": "A", "literal": 1}, [])
        pipeline._q.put(stale)
        pipeline._q.put(live)
        pipeline.start()
        assert live.future.result(timeout=10)[0] == ("res", "A", 1)
        # the cancelled item never reached the executor at all
        assert fake.prepared == [{"shape": "A", "literal": 1}]
        assert pipeline.dispatched == 1
    finally:
        pipeline.stop()


def test_timeout_mid_fetch_skips_decode():
    fake = FakeMeshExec(fetch_latency=0.5)
    pipeline = DeviceQueryPipeline(mesh_exec=fake, start=False)
    try:
        decoded = []
        a = _Item({"shape": "A", "literal": 0}, [])
        b = _Item({"shape": "B", "literal": 1}, [])
        pipeline._q.put(a)
        pipeline._q.put(b)
        pipeline.start()
        assert fake.fetch_started.wait(timeout=5)
        a.future.cancel()  # times out while the batched fetch is in flight
        got_b = b.future.result(timeout=10)
        assert got_b[0] == ("res", "B", 1)
        assert a.future.cancelled()
    finally:
        pipeline.stop()


def test_all_timed_out_launches_never_fetched():
    fake = FakeMeshExec()
    pipeline = DeviceQueryPipeline(mesh_exec=fake, start=False)
    try:
        a = _Item({"shape": "A", "literal": 0}, [])
        b = _Item({"shape": "A", "literal": 1}, [])
        # dispatch on the calling thread (threads not running yet), then
        # cancel BOTH callers before the fetcher ever sees the entry
        entry, n = pipeline._dispatch_grouped([a, b], time.perf_counter())
        assert n == 2 and entry
        a.future.cancel()
        b.future.cancel()
        pipeline._fetchq.put(entry)
        pipeline.start()
        time.sleep(0.3)
        # the dead batch was dropped WITHOUT paying a host round trip
        assert fake.fetch_calls == []
    finally:
        pipeline.stop()


def test_shape_keyed_reuse_one_executable_for_n_queries():
    fake = FakeMeshExec()
    pipeline = DeviceQueryPipeline(mesh_exec=fake, start=False)
    try:
        # five same-shape (different literal), one different shape, one
        # byte-identical duplicate of the first
        ctxs = ([{"shape": "A", "literal": i} for i in range(5)]
                + [{"shape": "B", "literal": 99}]
                + [{"shape": "A", "literal": 0}])
        results = _submit_concurrently(pipeline, ctxs)
        assert all(r is not DEVICE_FALLBACK for r in results)
        # 7 queries -> 6 dedupe groups -> 2 launches (A stacked, B solo)
        assert len(fake.launched_keys) == 2
        assert set(fake.launched_keys) == {("shape", "A"), ("shape", "B")}
        s = pipeline.stats()
        assert s["dispatched"] == 7
        assert s["launches"] == 2
        assert s["dedupeHits"] == 1
        assert s["stackedLaunches"] == 1
        # the duplicate decoded from the SAME launch result as the original
        assert results[6] == results[0]
    finally:
        pipeline.stop()


def test_fallback_and_stage_timings():
    fake = FakeMeshExec()
    pipeline = DeviceQueryPipeline(mesh_exec=fake, start=False)
    try:
        results = _submit_concurrently(
            pipeline, [{"shape": "A", "literal": 1},
                       {"shape": "A", "literal": 2, "fallback": True}])
        assert results[0][0] == ("res", "A", 1)
        assert results[1] is DEVICE_FALLBACK
        s = pipeline.stats()
        assert s["fallbacks"] == 1
        for stage in ("queue_wait", "dispatch", "fetch", "decode"):
            assert s["stageMs"][stage]["count"] >= 1, stage
    finally:
        pipeline.stop()


def test_legacy_executor_without_prepared_api():
    class LegacyExec:
        def __init__(self):
            self.calls = 0

        def dispatch_partial(self, ctx, segments):
            self.calls += 1
            if ctx.get("fallback"):
                return None
            return {"x": ctx["literal"]}, (lambda outs: ("legacy",
                                                         outs["x"]))

    legacy = LegacyExec()
    pipeline = DeviceQueryPipeline(mesh_exec=legacy, start=False)
    try:
        results = _submit_concurrently(
            pipeline, [{"literal": 7}, {"literal": 8, "fallback": True}])
        assert results[0] == ("legacy", 7)
        assert results[1] is DEVICE_FALLBACK
        assert legacy.calls == 2
    finally:
        pipeline.stop()


def test_smoke_real_executor_mean_batch_gt_one(tmp_path, ssb_schema):
    """CI smoke (tier-1, CPU mesh): a real QuickCluster + real
    MeshQueryExecutor under a small concurrent workload MUST batch —
    meanBatch > 1 or the served path has regressed to one query per
    round trip."""
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    pipeline = DeviceQueryPipeline(start=False)
    cluster.servers[0].device_pipeline = pipeline
    rng = np.random.default_rng(11)
    cfg = TableConfig(ssb_schema.name)
    cluster.create_table(ssb_schema, cfg)
    cluster.ingest_columns(cfg, make_ssb_columns(rng, 1500))
    try:
        sqls = [("SELECT COUNT(*), SUM(lo_revenue) FROM lineorder "
                 f"WHERE lo_quantity >= {q}") for q in (5, 15, 25, 35)]
        results = [None] * len(sqls)

        def run(i):
            results[i] = cluster.query(sqls[i])

        threads = [threading.Thread(target=run, args=(i,))
                   for i in range(len(sqls))]
        for t in threads:
            t.start()
        deadline = time.time() + 10
        while pipeline._q.qsize() < len(sqls) and time.time() < deadline:
            time.sleep(0.01)
        pipeline.start()
        for t in threads:
            t.join(timeout=60)
        s = pipeline.stats()
        assert s["dispatched"] == len(sqls)
        assert s["meanBatch"] > 1, s
        assert all(r is not None and r.rows for r in results)
    finally:
        pipeline.stop()
