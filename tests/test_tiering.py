"""Storage tiers: TierConfig + SegmentRelocator moving aged segments to tagged pools.

Reference: spi/config/table/TierConfig (time-based selector, pinot_server storage)
applied by the SegmentRelocator periodic task
(controller/helix/core/relocation/SegmentRelocator.java).
"""

import os
import time

import numpy as np
import pytest

from pinot_tpu.cluster import QuickCluster
from pinot_tpu.cluster.server import ServerNode
from pinot_tpu.schema import DataType, Schema, date_time, dimension, metric
from pinot_tpu.table import TableConfig, TierConfig


@pytest.fixture()
def tiered_cluster(tmp_path):
    cluster = QuickCluster(num_servers=2, work_dir=str(tmp_path))
    cold = ServerNode("server_cold", cluster.catalog, cluster.deepstore,
                      os.path.join(str(tmp_path), "server_cold"),
                      tags=["cold"], completion=cluster.controller.llc)
    cluster.broker.register_server_handle(
        cold.instance_id, cold.execute_partial,
        explain_handle=cold.explain_partial)
    cluster.servers.append(cold)
    return cluster


def _schema():
    return Schema("events", [dimension("k", DataType.STRING),
                             metric("v", DataType.DOUBLE),
                             date_time("ts", DataType.LONG)])


def _cols(n, ts_ms):
    return {"k": [f"k{i % 5}" for i in range(n)],
            "v": np.arange(n, dtype=np.float64),
            "ts": np.full(n, ts_ms, dtype=np.int64)}


def test_tier_config_roundtrip():
    cfg = TableConfig("events", tiers=[TierConfig("cold", 7.0, "cold")])
    back = TableConfig.from_json(cfg.to_json())
    assert back.tiers == [TierConfig("cold", 7.0, "cold")]


def test_aged_segment_relocates_to_cold_pool(tiered_cluster):
    cluster = tiered_cluster
    now_ms = int(time.time() * 1000)
    cfg = TableConfig("events", replication=1, time_column="ts",
                      tiers=[TierConfig("cold", 7.0, "cold")])
    cluster.create_table(_schema(), cfg)
    table = cfg.table_name_with_type
    cluster.ingest_columns(cfg, _cols(100, now_ms))                   # fresh
    cluster.ingest_columns(cfg, _cols(80, now_ms - 30 * 86_400_000))  # 30d old

    ist = cluster.catalog.ideal_state[table]
    assert all(set(a) <= {"server_0", "server_1"} for a in ist.values())

    moved = cluster.controller.run_segment_relocation()
    assert len(moved) == 1 and moved[0].endswith("->cold"), moved

    ist = cluster.catalog.ideal_state[table]
    by_age = {}
    for seg, meta in cluster.catalog.segments[table].items():
        by_age[seg] = meta.end_time_ms
    old_seg = min(by_age, key=by_age.get)
    fresh_seg = max(by_age, key=by_age.get)
    assert set(ist[old_seg]) == {"server_cold"}
    assert set(ist[fresh_seg]) <= {"server_0", "server_1"}

    # idempotent once converged
    assert cluster.controller.run_segment_relocation() == []

    # data remains fully queryable after the move
    res = cluster.query("SELECT COUNT(*) FROM events")
    assert res.rows[0][0] == 180
    res = cluster.query(f"SELECT COUNT(*) FROM events WHERE ts < {now_ms - 86_400_000}")
    assert res.rows[0][0] == 80


def test_empty_tier_pool_never_strands_segments(tmp_path):
    cluster = QuickCluster(num_servers=2, work_dir=str(tmp_path))
    now_ms = int(time.time() * 1000)
    cfg = TableConfig("events", replication=1, time_column="ts",
                      tiers=[TierConfig("cold", 7.0, "cold")])  # no cold servers
    cluster.create_table(_schema(), cfg)
    cluster.ingest_columns(cfg, _cols(50, now_ms - 30 * 86_400_000))
    assert cluster.controller.run_segment_relocation() == []
    assert cluster.query("SELECT COUNT(*) FROM events").rows[0][0] == 50


def test_multiple_tiers_oldest_threshold_wins(tiered_cluster):
    cluster = tiered_cluster
    frozen = ServerNode("server_frozen", cluster.catalog, cluster.deepstore,
                        os.path.join(cluster.work_dir, "server_frozen"),
                        tags=["frozen"], completion=cluster.controller.llc)
    cluster.broker.register_server_handle(
        frozen.instance_id, frozen.execute_partial,
        explain_handle=frozen.explain_partial)
    now_ms = int(time.time() * 1000)
    cfg = TableConfig("events", replication=1, time_column="ts",
                      tiers=[TierConfig("cold", 7.0, "cold"),
                             TierConfig("frozen", 90.0, "frozen")])
    cluster.create_table(_schema(), cfg)
    table = cfg.table_name_with_type
    cluster.ingest_columns(cfg, _cols(10, now_ms - 30 * 86_400_000))    # cold
    cluster.ingest_columns(cfg, _cols(10, now_ms - 200 * 86_400_000))   # frozen

    moved = sorted(cluster.controller.run_segment_relocation())
    assert len(moved) == 2
    assert any(m.endswith("->cold") for m in moved)
    assert any(m.endswith("->frozen") for m in moved)
    ist = cluster.catalog.ideal_state[table]
    pools = sorted(tuple(sorted(a)) for a in ist.values())
    assert pools == [("server_cold",), ("server_frozen",)]
    assert cluster.query("SELECT COUNT(*) FROM events").rows[0][0] == 20


def test_relocation_spreads_over_pool_and_skips_consuming(tmp_path):
    """A batch of aged segments must spread across the tier pool (not dogpile
    the first server), and consuming (IN_PROGRESS) segments never relocate."""
    from pinot_tpu.cluster.catalog import STATUS_IN_PROGRESS
    cluster = QuickCluster(num_servers=2, work_dir=str(tmp_path))
    for i in (1, 2):
        cold = ServerNode(f"server_cold{i}", cluster.catalog, cluster.deepstore,
                          os.path.join(str(tmp_path), f"server_cold{i}"),
                          tags=["cold"], completion=cluster.controller.llc)
        cluster.broker.register_server_handle(
            cold.instance_id, cold.execute_partial,
            explain_handle=cold.explain_partial)
    now_ms = int(time.time() * 1000)
    cfg = TableConfig("events", replication=1, time_column="ts",
                      tiers=[TierConfig("cold", 7.0, "cold")])
    cluster.create_table(_schema(), cfg)
    table = cfg.table_name_with_type
    for _ in range(6):
        cluster.ingest_columns(cfg, _cols(20, now_ms - 30 * 86_400_000))
    # one fake consuming segment must be left alone
    from pinot_tpu.cluster.catalog import SegmentMeta
    cluster.catalog.put_segment_meta(SegmentMeta(
        name="events__0__0__x", table=table, status=STATUS_IN_PROGRESS,
        partition_group=0, sequence_number=0, start_offset="0"))
    cluster.catalog.update_ideal_state(
        table, {"events__0__0__x": {"server_0": "CONSUMING"}})

    moved = cluster.controller.run_segment_relocation()
    assert len(moved) == 6
    ist = cluster.catalog.ideal_state[table]
    placements = [next(iter(a)) for seg, a in ist.items()
                  if seg != "events__0__0__x"]
    assert set(placements) == {"server_cold1", "server_cold2"}
    counts = {s: placements.count(s) for s in set(placements)}
    assert all(c == 3 for c in counts.values()), counts
    assert ist["events__0__0__x"] == {"server_0": "CONSUMING"}


def test_tenant_listing_and_retag(tmp_path):
    """Tenant = tag on server instances (reference: PinotTenantRestletResource,
    updateInstanceTags): re-tagging moves a server between pools; assignment
    follows on the next relocation pass."""
    cluster = QuickCluster(num_servers=2, work_dir=str(tmp_path))
    tenants = cluster.controller.list_tenants()
    assert tenants == {"DefaultTenant": ["server_0", "server_1"]}

    cluster.controller.update_instance_tags("server_1", ["cold"])
    tenants = cluster.controller.list_tenants()
    assert tenants == {"DefaultTenant": ["server_0"], "cold": ["server_1"]}

    import pytest as _pytest
    with _pytest.raises(ValueError):
        cluster.controller.update_instance_tags("nope", ["x"])

    # a tiered table now relocates aged segments onto the re-tagged server
    now_ms = int(time.time() * 1000)
    cfg = TableConfig("events", replication=1, time_column="ts",
                      tiers=[TierConfig("cold", 7.0, "cold")])
    cluster.create_table(_schema(), cfg)
    cluster.ingest_columns(cfg, _cols(30, now_ms - 30 * 86_400_000))
    moved = cluster.controller.run_segment_relocation()
    assert len(moved) == 1
    ist = cluster.catalog.ideal_state[cfg.table_name_with_type]
    assert all(set(a) == {"server_1"} for a in ist.values())
