"""High-cardinality group-by: the chunked 64x64 kernel path + dense decode.

Covers the r5 redesign (VERDICT r4 #2): cardinalities ABOVE the skinny
matmul cap take `_grouped_chunk64` (engine/kernels.py), and full results on
the mesh path decode through the vectorized `query/dense_reduce.py` instead
of the per-group state loop. Differentials pin both against the host
(numpy) engine. Reference behavior:
DictionaryBasedGroupKeyGenerator.java:62 + GroupByDataTableReducer.java.
"""

import numpy as np
import pytest

from pinot_tpu.engine.kernels import CHUNK_KEY_CAP, MATMUL_KEY_CAP
from pinot_tpu.parallel import MeshQueryExecutor, default_mesh
from pinot_tpu.query.executor import ServerQueryExecutor
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.segment import load_segment
from pinot_tpu.segment.writer import build_aligned_segments

N_KEYS = 2500  # > MATMUL_KEY_CAP -> the chunked kernel branch
ROWS = 60_000


@pytest.fixture(scope="module")
def hc_schema():
    return Schema("hc", [
        dimension("k", DataType.INT),
        dimension("tag", DataType.STRING),
        metric("v", DataType.DOUBLE),
        metric("q", DataType.INT),
    ])


@pytest.fixture(scope="module")
def hc_cols():
    rng = np.random.default_rng(42)
    return {
        "k": rng.integers(0, N_KEYS, ROWS).astype(np.int32),
        "tag": [f"t{i}" for i in rng.integers(0, 7, ROWS)],
        "v": np.round(rng.uniform(-1000.0, 60_000.0, ROWS), 2),
        "q": rng.integers(1, 100, ROWS).astype(np.int32),
    }


@pytest.fixture(scope="module")
def hc_segments(tmp_path_factory, hc_schema, hc_cols):
    out = tmp_path_factory.mktemp("hc_aligned")
    paths = build_aligned_segments(hc_schema, hc_cols, str(out), "hc", 4)
    return [load_segment(p) for p in paths]


@pytest.fixture(scope="module")
def mesh_exec():
    return MeshQueryExecutor(default_mesh(4))


def test_cap_structure():
    assert MATMUL_KEY_CAP < N_KEYS + 1 <= CHUNK_KEY_CAP


HC_QUERIES = [
    # the BASELINE config-5 shape: high-card key, SUM + COUNT
    "SELECT k, SUM(v), COUNT(*) FROM hc GROUP BY k LIMIT 100000",
    # filter + avg/min/max riding the same chunked kernel
    "SELECT k, AVG(v), MIN(q), MAX(q) FROM hc WHERE q < 50 GROUP BY k "
    "ORDER BY k LIMIT 100000",
    # ORDER BY an aggregation, desc, with offset
    "SELECT k, SUM(v) FROM hc GROUP BY k ORDER BY SUM(v) DESC LIMIT 50",
    # variance family over the chunked power sums
    "SELECT k, VARPOP(q), STDDEVPOP(q) FROM hc GROUP BY k ORDER BY k "
    "LIMIT 100000",
]


@pytest.mark.parametrize("sql", HC_QUERIES)
def test_chunked_kernel_matches_host(hc_segments, mesh_exec, sql):
    dev = mesh_exec.execute(hc_segments, sql)
    host = ServerQueryExecutor(use_device=False).execute(hc_segments, sql)
    assert len(dev.rows) == len(host.rows)
    dev_rows, host_rows = dev.rows, host.rows
    if "ORDER BY" not in sql:
        # without ORDER BY row order is unspecified (host: first-seen merge
        # order; dense decode: key order) — compare as sets keyed on col 0
        dev_rows = sorted(dev_rows, key=lambda r: r[0])
        host_rows = sorted(host_rows, key=lambda r: r[0])
    for dr, hr in zip(dev_rows, host_rows):
        assert len(dr) == len(hr)
        for dv, hv in zip(dr, hr):
            if isinstance(dv, float) and isinstance(hv, float):
                assert abs(dv - hv) <= 2e-3 * max(1.0, abs(hv)), (dr, hr)
            else:
                assert dv == hv, (dr, hr)


def test_dense_decode_is_used(hc_segments, mesh_exec):
    res = mesh_exec.execute(hc_segments,
                            "SELECT k, SUM(v), COUNT(*) FROM hc GROUP BY k "
                            "LIMIT 100000")
    assert res.stats.get("denseReduce") is True
    assert res.stats["numGroups"] == N_KEYS
    # exact differential against raw numpy
    got = {r[0]: (r[1], r[2]) for r in res.rows}
    assert sum(c for _, c in got.values()) == ROWS


def test_dense_decode_order_and_limit(hc_segments, mesh_exec, hc_cols):
    res = mesh_exec.execute(hc_segments,
                            "SELECT k, SUM(v) FROM hc GROUP BY k "
                            "ORDER BY SUM(v) DESC LIMIT 7")
    assert len(res.rows) == 7
    sums = np.zeros(N_KEYS)
    np.add.at(sums, hc_cols["k"], hc_cols["v"])
    want = np.argsort(-sums)[:7]
    got = [r[0] for r in res.rows]
    assert got == [int(w) for w in want]
    for r in res.rows:
        assert abs(r[1] - sums[r[0]]) < 2e-3 * max(1.0, abs(sums[r[0]]))


def test_dense_decode_string_group_order(hc_segments, mesh_exec):
    """ORDER BY a string group column: dict-id sort must equal value sort."""
    res = mesh_exec.execute(hc_segments,
                            "SELECT tag, COUNT(*) FROM hc GROUP BY tag "
                            "ORDER BY tag DESC LIMIT 10")
    tags = [r[0] for r in res.rows]
    assert tags == sorted(tags, reverse=True)


def test_grouped_distinct_chunked(hc_segments, mesh_exec, hc_cols):
    """Grouped DISTINCTCOUNT: the presence matrix rides _grouped_chunk64 when
    the (groups x ids) product space fits the chunk cap."""
    res = mesh_exec.execute(hc_segments,
                            "SELECT tag, DISTINCTCOUNT(q) FROM hc "
                            "GROUP BY tag ORDER BY tag LIMIT 10")
    ks = np.asarray(hc_cols["tag"])
    qs = np.asarray(hc_cols["q"])
    for tag, got in res.rows:
        assert got == len(np.unique(qs[ks == tag]))


def _norm(rows):
    out = []
    for r in rows:
        vals = []
        for v in r:
            if isinstance(v, float):
                vals.append(float(f"{v:.5g}"))
            else:
                vals.append(v)
        out.append(tuple(vals))
    return out


def _assert_rows_match(dev_rows, host_rows, ctxmsg):
    assert len(dev_rows) == len(host_rows), ctxmsg
    for dr, hr in zip(dev_rows, host_rows):
        assert len(dr) == len(hr), (ctxmsg, dr, hr)
        for dv, hv in zip(dr, hr):
            if isinstance(dv, float) and isinstance(hv, float):
                assert abs(dv - hv) <= 2e-3 * max(1.0, abs(hv)),                     (ctxmsg, dr, hr)
            else:
                assert dv == hv, (ctxmsg, dr, hr)


# one card per kernel regime: skinny matmul (<=512), chunked 64x64 (two
# points), and — via the g*k combined key space — past the chunk cap
@pytest.mark.parametrize("card", [300, 700, 5000, 40_000])
def test_groupby_fuzz_across_cap_regimes(tmp_path_factory, mesh_exec, card):
    """Seeded fuzz of GROUP BY across the three kernel regimes, with
    filters, agg mixes, and order/limit shapes — differential against the
    host engine."""
    seed = card % 97
    rng = np.random.default_rng(1000 + seed)
    rows = 30_000
    schema = Schema(f"fz{seed}", [
        dimension("k", DataType.INT),
        dimension("g", DataType.STRING),
        metric("v", DataType.DOUBLE),
        metric("q", DataType.INT),
    ])
    cols = {
        "k": rng.integers(0, card, rows).astype(np.int32),
        "g": [f"g{i}" for i in rng.integers(0, 6, rows)],
        "v": np.round(rng.uniform(-500, 500, rows), 3),
        "q": rng.integers(0, 1000, rows).astype(np.int32),
    }
    out = tmp_path_factory.mktemp(f"fz{seed}")
    paths = build_aligned_segments(schema, cols, str(out), f"fz{seed}", 4)
    segs = [load_segment(p) for p in paths]
    host = ServerQueryExecutor(use_device=False)
    shapes = [
        f"SELECT k, COUNT(*), SUM(v) FROM fz{seed} GROUP BY k "
        f"ORDER BY k LIMIT 100000",
        f"SELECT k, AVG(v), MIN(q), MAX(q) FROM fz{seed} WHERE q < 500 "
        f"GROUP BY k ORDER BY k LIMIT 100000",
        # multi-column group: the combined key space k*6 can cross caps
        f"SELECT g, k, SUM(v) FROM fz{seed} WHERE q >= 250 GROUP BY g, k "
        f"ORDER BY g, k LIMIT 100000",
        # the k tiebreak pins rank order when adjacent sums differ by
        # less than cross-engine float error
        f"SELECT k, SUM(v) FROM fz{seed} GROUP BY k "
        f"ORDER BY SUM(v) DESC, k LIMIT 13",
        f"SELECT g, VARPOP(v), COUNT(*) FROM fz{seed} GROUP BY g "
        f"ORDER BY g LIMIT 10",
    ]
    for sql in shapes:
        dev = mesh_exec.execute(segs, sql)
        want = host.execute(segs, sql)
        _assert_rows_match(dev.rows, want.rows, sql)
