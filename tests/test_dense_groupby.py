"""High-cardinality group-by: the chunked 64x64 kernel path + dense decode.

Covers the r5 redesign (VERDICT r4 #2): cardinalities ABOVE the skinny
matmul cap take `_grouped_chunk64` (engine/kernels.py), and full results on
the mesh path decode through the vectorized `query/dense_reduce.py` instead
of the per-group state loop. Differentials pin both against the host
(numpy) engine. Reference behavior:
DictionaryBasedGroupKeyGenerator.java:62 + GroupByDataTableReducer.java.
"""

import numpy as np
import pytest

from pinot_tpu.engine.kernels import CHUNK_KEY_CAP, MATMUL_KEY_CAP
from pinot_tpu.parallel import MeshQueryExecutor, default_mesh
from pinot_tpu.query.executor import ServerQueryExecutor
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.segment import load_segment
from pinot_tpu.segment.writer import (SegmentGeneratorConfig,
                                      build_aligned_segments)

N_KEYS = 2500  # > MATMUL_KEY_CAP -> the chunked kernel branch
ROWS = 60_000


@pytest.fixture(scope="module")
def hc_schema():
    return Schema("hc", [
        dimension("k", DataType.INT),
        dimension("tag", DataType.STRING),
        metric("v", DataType.DOUBLE),
        metric("q", DataType.INT),
    ])


@pytest.fixture(scope="module")
def hc_cols():
    rng = np.random.default_rng(42)
    return {
        "k": rng.integers(0, N_KEYS, ROWS).astype(np.int32),
        "tag": [f"t{i}" for i in rng.integers(0, 7, ROWS)],
        "v": np.round(rng.uniform(-1000.0, 60_000.0, ROWS), 2),
        "q": rng.integers(1, 100, ROWS).astype(np.int32),
    }


@pytest.fixture(scope="module")
def hc_segments(tmp_path_factory, hc_schema, hc_cols):
    out = tmp_path_factory.mktemp("hc_aligned")
    paths = build_aligned_segments(hc_schema, hc_cols, str(out), "hc", 4)
    return [load_segment(p) for p in paths]


@pytest.fixture(scope="module")
def mesh_exec():
    return MeshQueryExecutor(default_mesh(4))


def test_cap_structure():
    assert MATMUL_KEY_CAP < N_KEYS + 1 <= CHUNK_KEY_CAP


HC_QUERIES = [
    # the BASELINE config-5 shape: high-card key, SUM + COUNT
    "SELECT k, SUM(v), COUNT(*) FROM hc GROUP BY k LIMIT 100000",
    # filter + avg/min/max riding the same chunked kernel
    "SELECT k, AVG(v), MIN(q), MAX(q) FROM hc WHERE q < 50 GROUP BY k "
    "ORDER BY k LIMIT 100000",
    # ORDER BY an aggregation, desc, with offset
    "SELECT k, SUM(v) FROM hc GROUP BY k ORDER BY SUM(v) DESC LIMIT 50",
    # variance family over the chunked power sums
    "SELECT k, VARPOP(q), STDDEVPOP(q) FROM hc GROUP BY k ORDER BY k "
    "LIMIT 100000",
]


@pytest.mark.parametrize("sql", HC_QUERIES)
def test_chunked_kernel_matches_host(hc_segments, mesh_exec, sql):
    dev = mesh_exec.execute(hc_segments, sql)
    host = ServerQueryExecutor(use_device=False).execute(hc_segments, sql)
    assert len(dev.rows) == len(host.rows)
    dev_rows, host_rows = dev.rows, host.rows
    if "ORDER BY" not in sql:
        # without ORDER BY row order is unspecified (host: first-seen merge
        # order; dense decode: key order) — compare as sets keyed on col 0
        dev_rows = sorted(dev_rows, key=lambda r: r[0])
        host_rows = sorted(host_rows, key=lambda r: r[0])
    for dr, hr in zip(dev_rows, host_rows):
        assert len(dr) == len(hr)
        for dv, hv in zip(dr, hr):
            if isinstance(dv, float) and isinstance(hv, float):
                assert abs(dv - hv) <= 2e-3 * max(1.0, abs(hv)), (dr, hr)
            else:
                assert dv == hv, (dr, hr)


def test_dense_decode_is_used(hc_segments, mesh_exec):
    res = mesh_exec.execute(hc_segments,
                            "SELECT k, SUM(v), COUNT(*) FROM hc GROUP BY k "
                            "LIMIT 100000")
    assert res.stats.get("denseReduce") is True
    assert res.stats["numGroups"] == N_KEYS
    # exact differential against raw numpy
    got = {r[0]: (r[1], r[2]) for r in res.rows}
    assert sum(c for _, c in got.values()) == ROWS


def test_dense_decode_order_and_limit(hc_segments, mesh_exec, hc_cols):
    res = mesh_exec.execute(hc_segments,
                            "SELECT k, SUM(v) FROM hc GROUP BY k "
                            "ORDER BY SUM(v) DESC LIMIT 7")
    assert len(res.rows) == 7
    sums = np.zeros(N_KEYS)
    np.add.at(sums, hc_cols["k"], hc_cols["v"])
    want = np.argsort(-sums)[:7]
    got = [r[0] for r in res.rows]
    assert got == [int(w) for w in want]
    for r in res.rows:
        assert abs(r[1] - sums[r[0]]) < 2e-3 * max(1.0, abs(sums[r[0]]))


def test_dense_decode_string_group_order(hc_segments, mesh_exec):
    """ORDER BY a string group column: dict-id sort must equal value sort."""
    res = mesh_exec.execute(hc_segments,
                            "SELECT tag, COUNT(*) FROM hc GROUP BY tag "
                            "ORDER BY tag DESC LIMIT 10")
    tags = [r[0] for r in res.rows]
    assert tags == sorted(tags, reverse=True)


def test_dense_orderby_null_ranking_matches_host(tmp_path_factory, mesh_exec):
    """Differential lock on ORDER BY null ranking: groups whose aggregation is
    null (every input cell null) must land in the same positions on the dense
    decode as on the classic host reduce, for every desc/nulls combination —
    the dense lexsort ranks NaN-as-null exactly like reduce._sort_key."""
    rng = np.random.default_rng(7)
    rows, card = 4000, 60
    schema = Schema("nul", [dimension("k", DataType.INT),
                            metric("v", DataType.DOUBLE)])
    k = rng.integers(0, card, rows).astype(np.int64)
    v = np.round(rng.uniform(-100, 100, rows), 3).astype(object)
    v[k < 6] = None            # six all-null groups -> null SUM(v)
    out = tmp_path_factory.mktemp("nulorder")
    cfg = SegmentGeneratorConfig(raw_cardinality_fraction=4.0,
                                 no_dictionary_columns=["v"])
    paths = build_aligned_segments(schema, {"k": k, "v": v}, str(out),
                                   "nul", 4, config=cfg)
    segs = [load_segment(p) for p in paths]
    host = ServerQueryExecutor(use_device=False)
    for suffix in ("", " DESC", " NULLS FIRST", " NULLS LAST",
                   " DESC NULLS FIRST", " DESC NULLS LAST"):
        sql = (f"SELECT k, SUM(v) FROM nul GROUP BY k "
               f"ORDER BY SUM(v){suffix}, k LIMIT 100")
        dev = mesh_exec.execute(segs, sql)
        want = host.execute(segs, sql)
        assert dev.stats.get("denseReduce") is True, sql
        assert [r[0] for r in dev.rows] == [r[0] for r in want.rows], sql
        for dr, wr in zip(dev.rows, want.rows):
            if wr[1] is None:
                assert dr[1] is None, sql
            else:
                assert abs(dr[1] - wr[1]) <= 2e-3 * max(1.0, abs(wr[1])), sql


def test_grouped_distinct_chunked(hc_segments, mesh_exec, hc_cols):
    """Grouped DISTINCTCOUNT: the presence matrix rides _grouped_chunk64 when
    the (groups x ids) product space fits the chunk cap."""
    res = mesh_exec.execute(hc_segments,
                            "SELECT tag, DISTINCTCOUNT(q) FROM hc "
                            "GROUP BY tag ORDER BY tag LIMIT 10")
    ks = np.asarray(hc_cols["tag"])
    qs = np.asarray(hc_cols["q"])
    for tag, got in res.rows:
        assert got == len(np.unique(qs[ks == tag]))


def _norm(rows):
    out = []
    for r in rows:
        vals = []
        for v in r:
            if isinstance(v, float):
                vals.append(float(f"{v:.5g}"))
            else:
                vals.append(v)
        out.append(tuple(vals))
    return out


def _assert_rows_match(dev_rows, host_rows, ctxmsg):
    assert len(dev_rows) == len(host_rows), ctxmsg
    for dr, hr in zip(dev_rows, host_rows):
        assert len(dr) == len(hr), (ctxmsg, dr, hr)
        for dv, hv in zip(dr, hr):
            if isinstance(dv, float) and isinstance(hv, float):
                assert abs(dv - hv) <= 2e-3 * max(1.0, abs(hv)),                     (ctxmsg, dr, hr)
            else:
                assert dv == hv, (ctxmsg, dr, hr)


# one card per kernel regime: skinny matmul (<=512), chunked 64x64 (two
# points), and — via the g*k combined key space — past the chunk cap
@pytest.mark.parametrize("card", [300, 700, 5000, 40_000])
def test_groupby_fuzz_across_cap_regimes(tmp_path_factory, mesh_exec, card):
    """Seeded fuzz of GROUP BY across the three kernel regimes, with
    filters, agg mixes, and order/limit shapes — differential against the
    host engine."""
    seed = card % 97
    rng = np.random.default_rng(1000 + seed)
    rows = 30_000
    schema = Schema(f"fz{seed}", [
        dimension("k", DataType.INT),
        dimension("g", DataType.STRING),
        metric("v", DataType.DOUBLE),
        metric("q", DataType.INT),
    ])
    cols = {
        "k": rng.integers(0, card, rows).astype(np.int32),
        "g": [f"g{i}" for i in rng.integers(0, 6, rows)],
        "v": np.round(rng.uniform(-500, 500, rows), 3),
        "q": rng.integers(0, 1000, rows).astype(np.int32),
    }
    out = tmp_path_factory.mktemp(f"fz{seed}")
    paths = build_aligned_segments(schema, cols, str(out), f"fz{seed}", 4)
    segs = [load_segment(p) for p in paths]
    host = ServerQueryExecutor(use_device=False)
    shapes = [
        f"SELECT k, COUNT(*), SUM(v) FROM fz{seed} GROUP BY k "
        f"ORDER BY k LIMIT 100000",
        f"SELECT k, AVG(v), MIN(q), MAX(q) FROM fz{seed} WHERE q < 500 "
        f"GROUP BY k ORDER BY k LIMIT 100000",
        # multi-column group: the combined key space k*6 can cross caps
        f"SELECT g, k, SUM(v) FROM fz{seed} WHERE q >= 250 GROUP BY g, k "
        f"ORDER BY g, k LIMIT 100000",
        # the k tiebreak pins rank order when adjacent sums differ by
        # less than cross-engine float error
        f"SELECT k, SUM(v) FROM fz{seed} GROUP BY k "
        f"ORDER BY SUM(v) DESC, k LIMIT 13",
        f"SELECT g, VARPOP(v), COUNT(*) FROM fz{seed} GROUP BY g "
        f"ORDER BY g LIMIT 10",
    ]
    for sql in shapes:
        dev = mesh_exec.execute(segs, sql)
        want = host.execute(segs, sql)
        _assert_rows_match(dev.rows, want.rows, sql)


# ---------------------------------------------------------------------------
# very-high-cardinality regimes: radix-partitioned + sort kernels (PR: the
# segment_sum scatter fallback replacement) — differential vs the host engine
# ---------------------------------------------------------------------------

from pinot_tpu.engine.calibrate import KernelCaps, get_caps, set_caps  # noqa: E402


@pytest.fixture(scope="module")
def vhc_segments(tmp_path_factory):
    """6000-key set: padded key space 8192 crosses a FORCED chunk_cap of 4096,
    so the sort-based regimes exercise cheaply in tier-1."""
    rng = np.random.default_rng(7)
    rows = 40_000
    schema = Schema("vhc", [
        dimension("k", DataType.INT),
        metric("v", DataType.DOUBLE),
        metric("q", DataType.INT),
    ])
    cols = {
        "k": rng.integers(0, 6000, rows).astype(np.int32),
        "v": np.round(rng.uniform(-500, 500, rows), 3),
        # group sums cross int32 (the overflow differential)
        "q": rng.integers(0, 1 << 30, rows).astype(np.int32),
    }
    out = tmp_path_factory.mktemp("vhc")
    paths = build_aligned_segments(schema, cols, str(out), "vhc", 4)
    return [load_segment(p) for p in paths]


def _assert_rows_close(dev_rows, host_rows, ctxmsg, rtol=1e-3):
    """Row-for-row match; numerics compare with relative tolerance (device
    sums accumulate in f32 via bf16 splits — int sums come back as floats)."""
    assert len(dev_rows) == len(host_rows), ctxmsg
    for dr, hr in zip(dev_rows, host_rows):
        assert len(dr) == len(hr), (ctxmsg, dr, hr)
        for dv, hv in zip(dr, hr):
            if isinstance(dv, bool) or isinstance(hv, bool) \
                    or not isinstance(dv, (int, float)) \
                    or not isinstance(hv, (int, float)):
                assert dv == hv, (ctxmsg, dr, hr)
            else:
                assert abs(dv - hv) <= rtol * max(1.0, abs(hv)), \
                    (ctxmsg, dr, hr)


VHC_QUERIES = [
    "SELECT k, COUNT(*), SUM(v) FROM vhc GROUP BY k ORDER BY k LIMIT 3000000",
    "SELECT k, SUM(q) FROM vhc GROUP BY k ORDER BY k LIMIT 3000000",
    "SELECT k, AVG(v), MIN(q), MAX(q) FROM vhc WHERE q < 900000000 GROUP BY k "
    "ORDER BY k LIMIT 3000000",
    "SELECT k, SUM(v) FROM vhc GROUP BY k ORDER BY SUM(v) DESC, k LIMIT 17",
]


@pytest.mark.parametrize("regime", ["partitioned", "sorted"])
def test_forced_high_card_regime_matches_host(vhc_segments, mesh_exec, regime):
    """Force chunk_cap below the padded key space so BOTH new sort-based
    kernels run through the full mesh stack, differentially vs the host."""
    host = ServerQueryExecutor(use_device=False)
    prev = get_caps()
    set_caps(KernelCaps(chunk_cap=4096, high_card_regime=regime))
    try:
        for sql in VHC_QUERIES:
            dev = mesh_exec.execute(vhc_segments, sql)
            want = host.execute(vhc_segments, sql)
            _assert_rows_close(dev.rows, want.rows, (regime, sql))
    finally:
        set_caps(prev)


def test_scatter_escape_hatch_matches_host(vhc_segments, mesh_exec):
    """high_card_regime='scatter' keeps the legacy segment_sum path alive."""
    host = ServerQueryExecutor(use_device=False)
    prev = get_caps()
    set_caps(KernelCaps(chunk_cap=4096, high_card_regime="scatter"))
    try:
        sql = VHC_QUERIES[0]
        dev = mesh_exec.execute(vhc_segments, sql)
        want = host.execute(vhc_segments, sql)
        _assert_rows_close(dev.rows, want.rows, ("scatter", sql))
    finally:
        set_caps(prev)


def _guaranteed_card_keys(rng, card, rows):
    """Exactly min(card, rows) distinct keys: one pass of every key, the rest
    random repeats. Pure random draws top out far below the nominal card
    (20k draws from 140k keys hit ~19k uniques) and would silently test the
    WRONG dispatch regime."""
    base = min(card, rows)
    k = np.concatenate([np.arange(base, dtype=np.int64),
                        rng.integers(0, base, rows - base)])
    rng.shuffle(k)
    return k.astype(np.int32)


def _very_high_card_case(tmp_path_factory, card, rows, with_nulls):
    rng = np.random.default_rng(card % 9973)
    schema = Schema("vh", [
        dimension("k", DataType.INT),
        metric("v", DataType.DOUBLE),
        metric("q", DataType.INT),
    ])
    v = np.round(rng.uniform(-500, 500, rows), 3)
    cols = {
        "k": _guaranteed_card_keys(rng, card, rows),
        "v": v,
        "q": rng.integers(0, 1 << 30, rows).astype(np.int32),
    }
    if with_nulls:
        vo = v.astype(object)
        vo[rng.random(rows) < 0.02] = None  # null cells -> NaN-aware aggs
        cols["v"] = vo
    out = tmp_path_factory.mktemp(f"vh{card}")
    # keep k dictionary-encoded even at cardinality ~= rows: the device
    # group-by only rides dict columns, and raw-encoding would demote every
    # query here to the host path (vacuously green differential). Metrics
    # stay raw — the fixed-dict encoder can't represent None cells.
    cfg = SegmentGeneratorConfig(raw_cardinality_fraction=4.0,
                                 no_dictionary_columns=["v", "q"])
    paths = build_aligned_segments(schema, cols, str(out), f"vh{card}", 4,
                                   config=cfg)
    segs = [load_segment(p) for p in paths]
    assert segs[0].column("k").dictionary is not None
    return segs


def _run_very_high_card(tmp_path_factory, mesh_exec, card, rows,
                        with_nulls=False):
    segs = _very_high_card_case(tmp_path_factory, card, rows, with_nulls)
    host = ServerQueryExecutor(use_device=False)
    shapes = [
        f"SELECT k, COUNT(*), SUM(v) FROM vh GROUP BY k "
        f"ORDER BY k LIMIT 3000000",
        f"SELECT k, SUM(q) FROM vh GROUP BY k ORDER BY k LIMIT 3000000",
        f"SELECT k, SUM(v) FROM vh GROUP BY k ORDER BY SUM(v) DESC, k "
        f"LIMIT 23",
    ]
    for sql in shapes:
        dev = mesh_exec.execute(segs, sql)
        want = host.execute(segs, sql)
        _assert_rows_close(dev.rows, want.rows, (card, sql))


def test_partitioned_regime_128k_groups(tmp_path_factory, mesh_exec):
    """Tier-1 anchor of the sweep: 140k REAL groups is past the default
    chunk_cap (131072), so the radix-partitioned kernel is the regime
    actually dispatched."""
    assert get_caps().high_card_regime == "partitioned"
    _run_very_high_card(tmp_path_factory, mesh_exec, 140_000, 160_000)


@pytest.mark.slow
@pytest.mark.parametrize("card,rows", [(500_000, 650_000),
                                       (2_000_000, 2_050_000)])
def test_very_high_card_fuzz_sweep(tmp_path_factory, mesh_exec, card, rows):
    _run_very_high_card(tmp_path_factory, mesh_exec, card, rows)


@pytest.mark.slow
def test_very_high_card_with_nulls(tmp_path_factory, mesh_exec):
    _run_very_high_card(tmp_path_factory, mesh_exec, 140_000, 160_000,
                        with_nulls=True)


def test_dense_partial_roundtrip(vhc_segments, mesh_exec):
    """Server partial at >=4096 groups ships the ARRAY form (DensePartial):
    wire roundtrip + elementwise merge + vectorized broker reduce must equal
    the classic end-to-end result."""
    import jax

    from pinot_tpu.cluster.wire import (decode_segment_result,
                                        encode_segment_result)
    from pinot_tpu.query.aggregates import make_agg
    from pinot_tpu.query.context import compile_query
    from pinot_tpu.query.reduce import (merge_segment_results,
                                        reduce_to_result)

    sql = ("SELECT k, COUNT(*), SUM(v) FROM vhc GROUP BY k "
           "ORDER BY k LIMIT 3000000")
    schema = vhc_segments[0].schema
    ctx = compile_query(sql, schema)
    halves = [vhc_segments[:2], vhc_segments[2:]]
    partials = []
    for half in halves:
        dp = mesh_exec.dispatch_partial(ctx, half)
        assert dp is not None, "device partial path refused the plan"
        outs_dev, decode = dp
        part = decode(jax.device_get(outs_dev))
        assert part.dense is not None, "expected the array-form partial"
        assert len(part.groups) == 0
        partials.append(part)
    # one partial crosses the wire (server -> broker), one stays local
    partials[0] = decode_segment_result(encode_segment_result(partials[0]))
    assert partials[0].dense is not None
    assert partials[0].dense.token == partials[1].dense.token
    aggs = [make_agg(f) for f in ctx.aggregations]
    merged = merge_segment_results(partials, aggs)
    assert merged.dense is not None, "aligned dense partials must merge dense"
    got = reduce_to_result(ctx, merged, aggs, list(ctx.group_by))
    want = ServerQueryExecutor(use_device=False).execute(vhc_segments, sql)
    _assert_rows_close(got.rows, want.rows, sql)


@pytest.mark.slow
def test_no_flat_scatter_at_high_card(tmp_path_factory):
    """Regression guard: the >=128k-group count+sum kernel must never lower
    through a flat scatter again (the 26.9M rows/s cliff this PR removes)."""
    import jax

    from pinot_tpu.engine import kernels
    from pinot_tpu.engine.datablock import block_for
    from pinot_tpu.query.context import compile_query
    from pinot_tpu.query.planner import build_device_geometry, plan_segment

    rng = np.random.default_rng(3)
    rows = 150_000
    schema = Schema("sc", [
        dimension("k", DataType.INT),
        metric("v", DataType.DOUBLE),
    ])
    cols = {
        "k": _guaranteed_card_keys(rng, 140_000, rows),
        "v": rng.uniform(0, 10, rows),
    }
    out = tmp_path_factory.mktemp("sc")
    cfg = SegmentGeneratorConfig(raw_cardinality_fraction=4.0)
    paths = build_aligned_segments(schema, cols, str(out), "sc", 1, config=cfg)
    seg = load_segment(paths[0])
    ctx = compile_query("SELECT k, COUNT(*), SUM(v) FROM sc GROUP BY k "
                        "LIMIT 3000000", schema)
    plan = plan_segment(ctx, seg)
    assert plan.kind == "device"
    build_device_geometry(plan)
    assert plan.num_keys_pad > get_caps().chunk_cap
    block = block_for(seg)
    spec = kernels.KernelSpec(plan.filter_prog, plan.group_cols,
                              plan.num_keys_pad,
                              tuple((a, a.device_outputs) for a in plan.aggs),
                              {}, block.padded)
    inputs = ServerQueryExecutor()._kernel_inputs(plan, spec, block)
    body = kernels.make_kernel_body(spec)
    jaxpr = jax.make_jaxpr(body)(
        inputs.ids, inputs.vals, inputs.luts, inputs.iscal, inputs.fscal,
        inputs.nulls, inputs.valid, inputs.strides, inputs.agg_luts,
        inputs.docsets)
    assert "scatter" not in str(jaxpr), \
        ">=128k-group count+sum kernel dispatched through flat scatter"
