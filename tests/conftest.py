"""Test harness config: force a virtual 8-device CPU mesh before jax initializes.

Real multi-chip hardware is unavailable in CI; sharding/collective paths are validated on
XLA's host-platform virtual devices (the analog of the reference's single-JVM cluster tests,
`pinot-integration-test-base/.../ClusterTest.java:88` — no real cluster needed anywhere).
"""

import os
import sys

# Force the CPU backend with 8 virtual devices. On this box the environment pins
# JAX_PLATFORMS=axon (a tunneled TPU) and a sitecustomize hook registers the axon PJRT
# plugin at interpreter start — before any conftest code can run, and merely setting
# JAX_PLATFORMS=cpu afterwards still initializes (and can hang on) the tunnel. So
# `pytest_configure` below re-execs the interpreter once with a scrubbed environment;
# jax backend init is lazy, so re-exec before any test imports run jax ops is safe.
_REEXEC_MARKER = "PINOT_TPU_TEST_REEXEC"


def _needs_cpu_reexec() -> bool:
    return (os.environ.get(_REEXEC_MARKER) != "1"
            and (os.environ.get("JAX_PLATFORMS", "cpu") != "cpu"
                 or bool(os.environ.get("PALLAS_AXON_POOL_IPS"))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running tests excluded from the tier-1 `-m 'not slow'` run")
    if _needs_cpu_reexec():
        env = dict(os.environ)
        env.update({
            _REEXEC_MARKER: "1",
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",   # sitecustomize no-ops without this
            "PYTHONPATH": os.pathsep.join(
                p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                if p and "axon_site" not in p),
            "XLA_FLAGS": (env.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=8").strip(),
        })
        sys.stdout.flush()
        sys.stderr.flush()
        os.execve(sys.executable,
                  [sys.executable, "-m", "pytest", *config.invocation_params.args], env)


os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (xla_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import pytest  # noqa: E402

from pinot_tpu.schema import (DataType, Schema, date_time, dimension, metric)  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


@pytest.fixture(scope="session")
def ssb_schema():
    """A Star-Schema-Benchmark-flavored lineorder schema used across tests."""
    return Schema("lineorder", [
        dimension("lo_orderkey", DataType.LONG),
        dimension("lo_custkey", DataType.INT),
        dimension("lo_region", DataType.STRING),
        dimension("lo_category", DataType.STRING),
        dimension("lo_brand", DataType.STRING),
        date_time("lo_orderdate", DataType.INT),  # yyyymmdd int like SSB
        metric("lo_quantity", DataType.INT),
        metric("lo_extendedprice", DataType.DOUBLE),
        metric("lo_discount", DataType.INT),
        metric("lo_revenue", DataType.DOUBLE),
    ])


REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
CATEGORIES = [f"MFGR#{i}" for i in range(1, 6)]
BRANDS = [f"MFGR#{i}{j}" for i in range(1, 6) for j in range(1, 9)]


def make_ssb_columns(rng, n):
    """Generate random SSB-like lineorder data as a column dict."""
    return {
        "lo_orderkey": rng.integers(1, 10_000_000, n, dtype=np.int64),
        "lo_custkey": rng.integers(1, 30_000, n, dtype=np.int32),
        "lo_region": [REGIONS[i] for i in rng.integers(0, len(REGIONS), n)],
        "lo_category": [CATEGORIES[i] for i in rng.integers(0, len(CATEGORIES), n)],
        "lo_brand": [BRANDS[i] for i in rng.integers(0, len(BRANDS), n)],
        "lo_orderdate": (19920101 + rng.integers(0, 7, n) * 10000
                         + rng.integers(1, 13, n) * 100 + rng.integers(1, 29, n)).astype(np.int32),
        "lo_quantity": rng.integers(1, 51, n, dtype=np.int32),
        "lo_extendedprice": np.round(rng.uniform(1.0, 10_000.0, n), 2),
        "lo_discount": rng.integers(0, 11, n, dtype=np.int32),
        "lo_revenue": np.round(rng.uniform(1.0, 60_000.0, n), 2),
    }


@pytest.fixture(scope="session")
def ssb_segment_dir(tmp_path_factory, rng, ssb_schema):
    """One built SSB segment on disk, shared across the test session."""
    from pinot_tpu.segment import SegmentBuilder, SegmentGeneratorConfig
    cols = make_ssb_columns(rng, 4096)
    builder = SegmentBuilder(ssb_schema, SegmentGeneratorConfig(
        inverted_index_columns=["lo_region", "lo_category"],
        range_index_columns=["lo_discount"],
        bloom_filter_columns=["lo_brand"],
    ))
    out = tmp_path_factory.mktemp("segments")
    return builder.build(cols, str(out), "lineorder_0"), cols


def wait_until(fn, timeout: float = 20.0, interval: float = 0.2,
               swallow: tuple = (Exception,)) -> bool:
    """Poll until fn() is truthy (catalog-mirror convergence etc.); exceptions
    in `swallow` count as not-yet (transient 500s during convergence)."""
    import time as _t
    deadline = _t.time() + timeout
    while _t.time() < deadline:
        try:
            if fn():
                return True
        except swallow:
            pass
        _t.sleep(interval)
    return False
