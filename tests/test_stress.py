"""Concurrency stress: queries racing ingestion, reloads, rebalance, and
commits on one in-proc cluster.

Reference pattern: ChaosMonkeyIntegrationTest + the reference's reliance on
refcounted segment acquire/release, volatile consuming-segment row counters,
and EV-converge loops. The engine's invariants under fire:
- no query ever throws (partial results are fine, errors are not),
- COUNT(*) is monotonically non-decreasing as ingestion progresses,
- after the dust settles, totals are exact.
"""

import json
import threading
import time

import numpy as np
import pytest

from pinot_tpu.cluster import QuickCluster
from pinot_tpu.ingest.stream import MemoryStream
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.table import IndexingConfig, StreamConfig, TableConfig, TableType


@pytest.fixture(autouse=True)
def _reset_streams():
    MemoryStream.reset_all()
    yield
    MemoryStream.reset_all()


def test_queries_race_ingestion_reload_rebalance(tmp_path):
    cluster = QuickCluster(num_servers=2, work_dir=str(tmp_path))
    schema = Schema("s", [dimension("k"), metric("v", DataType.DOUBLE)])
    cfg = TableConfig("s", replication=1)
    cluster.create_table(schema, cfg)

    stop = threading.Event()
    errors: list = []
    counts: list = []

    def querier():
        last = 0
        while not stop.is_set():
            try:
                n = cluster.query("SELECT COUNT(*) FROM s").rows[0][0]
                g = cluster.query("SELECT k, SUM(v) FROM s GROUP BY k "
                                  "ORDER BY k LIMIT 50").rows
                if n < last:
                    errors.append(f"count went backwards: {last} -> {n}")
                last = n
                counts.append(n)
                assert all(len(r) == 2 for r in g)
            except Exception as e:  # pragma: no cover - failure capture
                errors.append(f"query: {type(e).__name__}: {e}")
                return

    def reloader():
        flip = False
        while not stop.is_set():
            try:
                flip = not flip
                cfg.indexing = IndexingConfig(
                    inverted_index_columns=["k"] if flip else [])
                cluster.controller.update_table(cfg)
                time.sleep(0.02)
            except Exception as e:  # pragma: no cover
                errors.append(f"reload: {type(e).__name__}: {e}")
                return

    def rebalancer():
        while not stop.is_set():
            try:
                cluster.controller.rebalance("s_OFFLINE")
                time.sleep(0.05)
            except Exception as e:  # pragma: no cover
                errors.append(f"rebalance: {type(e).__name__}: {e}")
                return

    threads = [threading.Thread(target=f) for f in (querier, querier,
                                                    reloader, rebalancer)]
    for t in threads:
        t.start()

    total = 0
    rng = np.random.default_rng(3)
    try:
        for i in range(12):
            n = int(rng.integers(50, 200))
            cluster.ingest_columns(cfg, {
                "k": [f"k{j % 20}" for j in range(n)],
                "v": rng.uniform(0, 10, n)})
            total += n
            time.sleep(0.02)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)

    assert not errors, errors[:5]
    assert counts, "querier never completed a query"
    res = cluster.query("SELECT COUNT(*), SUM(v) FROM s")
    assert res.rows[0][0] == total


def test_realtime_commits_race_queries(tmp_path):
    cluster = QuickCluster(num_servers=2, work_dir=str(tmp_path))
    schema = Schema("rt", [dimension("u"), metric("m", DataType.DOUBLE)])
    cfg = TableConfig("rt", table_type=TableType.REALTIME, replication=2,
                      stream=StreamConfig(stream_type="memory", topic="st_t",
                                          decoder="json",
                                          flush_threshold_rows=40))
    cluster.create_realtime_table(schema, cfg, 2)
    stream = MemoryStream.get("st_t")
    table = cfg.table_name_with_type

    stop = threading.Event()
    errors: list = []

    def querier():
        last = 0
        while not stop.is_set():
            try:
                n = cluster.query("SELECT COUNT(*) FROM rt").rows[0][0]
                if n < last:
                    errors.append(f"count regressed {last} -> {n}")
                last = n
            except Exception as e:  # pragma: no cover
                errors.append(f"query: {type(e).__name__}: {e}")
                return

    threads = [threading.Thread(target=querier) for _ in range(2)]
    for t in threads:
        t.start()
    total = 0
    try:
        for burst in range(10):
            for i in range(35):
                stream.produce(json.dumps({"u": f"u{i % 9}", "m": 1.0}),
                               partition=burst % 2)
                total += 1
            # drive consumption + completion protocol rounds concurrently
            # with the query threads
            for _ in range(3):
                cluster.pump_realtime(table)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)

    assert not errors, errors[:5]
    for _ in range(4):
        cluster.pump_realtime(table)
    res = cluster.query("SELECT COUNT(*), SUM(m) FROM rt")
    assert res.rows[0][0] == total
    assert res.rows[0][1] == pytest.approx(float(total))
