"""Realtime ingestion across REAL OS processes: kafkalite over TCP, consumers
pumping themselves (auto_consume), completion protocol over HTTP.

This is the full distributed realtime shape (reference:
LLCRealtimeClusterIntegrationTest with actual Kafka + separate role JVMs):
the test process runs only the socket log broker and the clients; the
controller and server are separate processes joined over HTTP.
"""

import json
import time

import pytest

from pinot_tpu.cluster.process import ProcessCluster
from pinot_tpu.ingest.kafkalite import LogBrokerClient, LogBrokerServer
from pinot_tpu.schema import DataType, Schema, date_time, dimension, metric
from pinot_tpu.table import StreamConfig, TableConfig, TableType

from conftest import wait_until


@pytest.fixture()
def log_broker():
    srv = LogBrokerServer()   # accept loop starts in the constructor
    yield srv
    srv.stop()


def test_realtime_over_processes(tmp_path, log_broker):
    schema = Schema("clicks", [
        dimension("user", DataType.STRING),
        metric("value", DataType.LONG),
        date_time("ts", DataType.LONG),
    ])
    client = LogBrokerClient(log_broker.bootstrap)
    client.create_topic("clicks", 1)

    with ProcessCluster(num_servers=1, work_dir=str(tmp_path)) as cluster:
        cluster.controller.add_schema(schema)
        cfg = TableConfig(
            "clicks", table_type=TableType.REALTIME, time_column="ts",
            stream=StreamConfig(stream_type="kafkalite", topic="clicks",
                                properties={"bootstrap": log_broker.bootstrap},
                                flush_threshold_rows=20))
        cluster.controller.add_table(cfg, num_partitions=1)

        for i in range(15):
            client.produce("clicks", json.dumps(
                {"user": f"u{i % 3}", "value": i, "ts": 1700000000000 + i}))

        # the SERVER PROCESS consumes on its own loop (auto_consume): rows
        # become queryable with zero driving from this process
        def count():
            rows = cluster.query("SELECT COUNT(*) FROM clicks")[
                "resultTable"]["rows"]
            return rows[0][0] if rows else 0
        assert wait_until(lambda: count() == 15, timeout=30), count()

        # cross the flush threshold: the completion protocol (segment consumed/
        # commitStart/commitEnd + tar upload) runs over HTTP to the controller
        for i in range(15, 30):
            client.produce("clicks", json.dumps(
                {"user": f"u{i % 3}", "value": i, "ts": 1700000000000 + i}))
        assert wait_until(lambda: count() == 30, timeout=30), count()

        def committed_segments():
            metas = cluster.controller.segments_meta(
                cfg.table_name_with_type)["segments"]
            return [m for m in metas.values() if m.get("status") == "DONE"]
        assert wait_until(lambda: len(committed_segments()) >= 1, timeout=30), \
            "segment must commit through the HTTP completion protocol"

        # no data lost or duplicated through the commit + successor handoff
        rows = cluster.query("SELECT user, SUM(value) FROM clicks GROUP BY user "
                             "ORDER BY user LIMIT 5")["resultTable"]["rows"]
        want = {}
        for i in range(30):
            want[f"u{i % 3}"] = want.get(f"u{i % 3}", 0) + i
        assert {r[0]: r[1] for r in rows} == want


def test_consuming_server_killed_and_restarted_replays_offsets(tmp_path, log_broker):
    """SIGKILL the consuming server mid-stream, restart it under the same id:
    the new process resumes from the CHECKPOINTED offsets (committed segment
    metadata), so every produced row appears exactly once — no loss from the
    crash, no duplicates from the replay (reference: CONSUMING segment replay
    from SegmentZKMetadata start offsets after server restart)."""
    schema = Schema("evr", [
        dimension("user", DataType.STRING),
        metric("value", DataType.LONG),
        date_time("ts", DataType.LONG),
    ])
    client = LogBrokerClient(log_broker.bootstrap)
    client.create_topic("evr", 1)

    with ProcessCluster(num_servers=1, work_dir=str(tmp_path)) as cluster:
        cluster.controller.add_schema(schema)
        cfg = TableConfig(
            "evr", table_type=TableType.REALTIME, time_column="ts",
            stream=StreamConfig(stream_type="kafkalite", topic="evr",
                                properties={"bootstrap": log_broker.bootstrap},
                                flush_threshold_rows=25))
        cluster.controller.add_table(cfg, num_partitions=1)

        def count():
            rows = cluster.query("SELECT COUNT(*) FROM evr")[
                "resultTable"]["rows"]
            return rows[0][0] if rows else 0

        # phase 1: enough rows to force >=1 commit (durable) + a consuming tail
        for i in range(40):
            client.produce("evr", json.dumps(
                {"user": f"u{i % 3}", "value": i, "ts": 1700000000000 + i}))
        assert wait_until(lambda: count() == 40, timeout=30), count()

        def committed():
            metas = cluster.controller.segments_meta(
                cfg.table_name_with_type)["segments"]
            return [m for m in metas.values() if m.get("status") == "DONE"]
        assert wait_until(lambda: len(committed()) >= 1, timeout=30)

        cluster.kill_server("server_0")
        # rows produced while the server is DEAD must appear after restart
        for i in range(40, 55):
            client.produce("evr", json.dumps(
                {"user": f"u{i % 3}", "value": i, "ts": 1700000000000 + i}))

        cluster.restart_server("server_0")
        assert wait_until(lambda: count() == 55, timeout=60), count()

        # exactly-once through crash + replay: per-user sums match the stream
        rows = cluster.query("SELECT user, SUM(value) FROM evr GROUP BY user "
                             "ORDER BY user LIMIT 10")["resultTable"]["rows"]
        want = {}
        for i in range(55):
            want[f"u{i % 3}"] = want.get(f"u{i % 3}", 0) + i
        assert {r[0]: r[1] for r in rows} == want
