"""Protobuf tests: wire golden vectors, descriptor parsing via REAL protoc
output, round-trips, and end-to-end batch + realtime ingestion.

Mirrors the reference's protobuf plugin coverage
(`pinot-plugins/pinot-input-format/pinot-protobuf/src/test/...`). protoc
ships in the image, so descriptor sets are genuine `--descriptor_set_out`
blobs, not hand-built fixtures.
"""

import json
import struct

import numpy as np
import pytest

from pinot_tpu.ingest.proto import (DescriptorPool, ProtoError,
                                    ProtoRecordReader, compile_proto,
                                    decode_message, encode_message,
                                    iter_fields, make_proto_decoder,
                                    read_uvarint, write_delimited,
                                    write_uvarint)

PROTO_SRC = """
syntax = "proto3";
package bench;

message Inner {
  string label = 1;
  double weight = 2;
}

message Event {
  string user = 1;
  int64 clicks = 2;
  double cost = 3;
  sint64 delta = 4;
  bool active = 5;
  fixed32 shard = 6;
  repeated int32 codes = 7;
  repeated string tags = 8;
  Inner inner = 9;
  bytes blob = 10;
}
"""


@pytest.fixture(scope="module")
def pool(tmp_path_factory):
    desc = compile_proto(PROTO_SRC, str(tmp_path_factory.mktemp("proto")))
    return DescriptorPool(desc), desc


# -- wire golden vectors (protobuf encoding spec examples) -------------------

def test_golden_varints():
    assert write_uvarint(0) == b"\x00"
    assert write_uvarint(1) == b"\x01"
    assert write_uvarint(300) == b"\xac\x02"     # the spec's classic example
    assert read_uvarint(b"\xac\x02", 0) == (300, 2)


def test_golden_field_tags():
    # spec: message Test1 { int32 a = 1; } with a=150 -> 08 96 01
    fields = list(iter_fields(b"\x08\x96\x01"))
    assert fields == [(1, 0, 150)]
    # field 2, string "testing" -> 12 07 74 65 73 74 69 6e 67
    fields = list(iter_fields(b"\x12\x07testing"))
    assert fields == [(2, 2, b"testing")]


ROW = {
    "user": "alice",
    "clicks": -42,
    "cost": 3.75,
    "delta": -7,
    "active": True,
    "shard": 9,
    "codes": [1, -2, 300],
    "tags": ["a", "b"],
    "inner": {"label": "x", "weight": 0.5},
    "blob": b"\x00\xff",
}


def test_roundtrip_against_own_codec(pool):
    p, _ = pool
    schema = p.message("bench.Event")
    data = encode_message(p, schema, ROW)
    out = decode_message(p, schema, data)
    assert out == ROW


def test_decode_against_protoc_encoded_bytes(tmp_path, pool):
    """protoc --encode produces the bytes; our decoder must read them (true
    wire compatibility, not self-consistency)."""
    import subprocess
    p, _desc = pool
    (tmp_path / "schema.proto").write_text(PROTO_SRC)
    text = ('user: "bob" clicks: 5 cost: 1.5 delta: -3 active: true '
            'shard: 2 codes: 1 codes: 2 tags: "t1" '
            'inner { label: "in" weight: 2.25 } blob: "hi"')
    enc = subprocess.run(
        ["protoc", f"--proto_path={tmp_path}", "--encode=bench.Event",
         str(tmp_path / "schema.proto")],
        input=text.encode(), capture_output=True, check=True)
    out = decode_message(p, p.message("bench.Event"), enc.stdout)
    assert out["user"] == "bob" and out["clicks"] == 5
    assert out["delta"] == -3 and out["active"] is True
    assert out["codes"] == [1, 2] and out["tags"] == ["t1"]
    assert out["inner"] == {"label": "in", "weight": 2.25}
    assert out["blob"] == b"hi"
    # and protoc can read OUR bytes back (encode direction)
    ours = encode_message(p, p.message("bench.Event"), out)
    dec = subprocess.run(
        ["protoc", f"--proto_path={tmp_path}", "--decode=bench.Event",
         str(tmp_path / "schema.proto")],
        input=ours, capture_output=True, check=True)
    assert b'user: "bob"' in dec.stdout and b"clicks: 5" in dec.stdout


def test_unknown_fields_skipped(pool):
    p, _ = pool
    schema = p.message("bench.Inner")
    # field 99 (unknown): varint — must be skipped, not error (weight absent
    # on the wire -> proto3 default 0.0)
    data = encode_message(p, schema, {"label": "x"}) + b"\x98\x06\x2a"
    assert decode_message(p, schema, data) == {"label": "x", "weight": 0.0}


def test_record_reader_with_sidecar(tmp_path, pool):
    p, desc = pool
    schema = p.message("bench.Event")
    rows = [dict(ROW, user=f"u{i}", clicks=i) for i in range(50)]
    path = str(tmp_path / "events.pb")
    write_delimited(path, p, schema, rows)
    (tmp_path / "events.pb.desc").write_bytes(desc)
    (tmp_path / "events.pb.msg").write_text("bench.Event")
    from pinot_tpu.ingest.readers import reader_for
    rdr = reader_for(path)
    got = list(rdr.rows())
    rdr.close()
    assert len(got) == 50
    assert got[7]["user"] == "u7" and got[7]["clicks"] == 7
    assert got[0]["inner"]["weight"] == 0.5


def test_truncated_delimited_file_errors(tmp_path, pool):
    p, desc = pool
    schema = p.message("bench.Event")
    path = str(tmp_path / "bad.pb")
    write_delimited(path, p, schema, [ROW])
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[:-3])
    with pytest.raises(ProtoError, match="truncated"):
        list(ProtoRecordReader(path, descriptor_set=desc,
                               message="bench.Event").rows())


def test_batch_ingestion_of_protobuf_differential(tmp_path, pool):
    """Same rows through .pb and .jsonl produce identical query results."""
    from pinot_tpu.cluster.enclosure import QuickCluster
    from pinot_tpu.ingest.batch import BatchIngestionJobSpec, run_batch_ingestion
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.table import TableConfig

    p, desc = pool
    schema_pb = p.message("bench.Event")
    rng = np.random.default_rng(3)
    rows = [{"user": f"u{int(x) % 40}", "clicks": int(c),
             "cost": round(float(v), 3)}
            for x, c, v in zip(rng.integers(0, 40, 400),
                               rng.integers(0, 9, 400),
                               rng.uniform(0, 5, 400))]
    pb_path = str(tmp_path / "ev.pb")
    write_delimited(pb_path, p, schema_pb, rows)
    (tmp_path / "ev.pb.desc").write_bytes(desc)
    (tmp_path / "ev.pb.msg").write_text("bench.Event")
    jsonl = tmp_path / "ev.jsonl"
    jsonl.write_text("".join(json.dumps(r) + "\n" for r in rows))

    schema = Schema("ev", [dimension("user"),
                           metric("clicks", DataType.LONG),
                           metric("cost", DataType.DOUBLE)])
    results = {}
    for fmt, path in [("pb", pb_path), ("jsonl", str(jsonl))]:
        cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path / fmt))
        cfg = TableConfig("ev")
        cluster.create_table(schema, cfg)
        run_batch_ingestion(
            BatchIngestionJobSpec(input_paths=[path],
                                  table=cfg.table_name_with_type,
                                  segment_rows=150),
            cluster.controller, work_dir=str(tmp_path / f"w_{fmt}"))
        results[fmt] = cluster.query(
            "SELECT user, COUNT(*), SUM(clicks), SUM(cost) FROM ev "
            "GROUP BY user ORDER BY user LIMIT 100").rows
    assert results["pb"] == results["jsonl"]


def test_realtime_table_consumes_protobuf(tmp_path, pool):
    """Realtime table decoding raw protobuf stream payloads via a registered
    decoder closure (reference: ProtoBufMessageDecoder)."""
    from pinot_tpu.cluster.enclosure import QuickCluster
    from pinot_tpu.ingest.stream import MemoryStream, register_decoder
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.table import StreamConfig, TableConfig, TableType

    p, desc = pool
    schema_pb = p.message("bench.Event")
    MemoryStream.reset_all()
    register_decoder("proto_events", make_proto_decoder(desc, "bench.Event"))
    try:
        cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
        schema = Schema("ev", [dimension("user"),
                               metric("clicks", DataType.LONG),
                               metric("cost", DataType.DOUBLE)])
        cfg = TableConfig("ev", table_type=TableType.REALTIME, replication=1,
                          stream=StreamConfig(stream_type="memory",
                                              topic="pb_topic",
                                              decoder="proto_events",
                                              flush_threshold_rows=1000))
        cluster.create_realtime_table(schema, cfg, 1)
        stream = MemoryStream.get("pb_topic")
        total = 0
        for i in range(200):
            total += i
            stream.produce(encode_message(p, schema_pb,
                                          {"user": f"u{i % 5}", "clicks": i,
                                           "cost": 0.5}), partition=0)
        cluster.pump_realtime(cfg.table_name_with_type)
        res = cluster.query("SELECT COUNT(*), SUM(clicks) FROM ev")
        assert res.rows[0] == [200, total]
    finally:
        MemoryStream.reset_all()


def test_proto3_implicit_defaults_filled(tmp_path, pool):
    """Review round: fields at their default value are omitted on the wire by
    proto3 producers; the decoder must fill 0/''/false/[], never drop keys."""
    import subprocess
    p, _ = pool
    (tmp_path / "schema.proto").write_text(PROTO_SRC)
    enc = subprocess.run(
        ["protoc", f"--proto_path={tmp_path}", "--encode=bench.Event",
         str(tmp_path / "schema.proto")],
        input=b'user: "u"', capture_output=True, check=True)
    out = decode_message(p, p.message("bench.Event"), enc.stdout)
    assert out["clicks"] == 0 and out["cost"] == 0.0
    assert out["active"] is False and out["blob"] == b""
    assert out["codes"] == [] and out["tags"] == []
    assert "inner" not in out            # absent submessage stays null


def test_packed_fixed_truncation_raises_proto_error(pool):
    from pinot_tpu.ingest.proto import _unpack_packed, T_FIXED64
    with pytest.raises(ProtoError, match="packed"):
        _unpack_packed(T_FIXED64, b"\x00" * 12)


def test_oneof_and_proto2_defaults_and_groups(tmp_path):
    """Review round 2: oneof arms (incl. proto3 optional) keep explicit
    presence; proto2 declared defaults fill; unknown legacy groups skip."""
    src2 = """
syntax = "proto2";
package p2;
message Legacy {
  optional int32 retries = 1 [default = 3];
  optional string mode = 2 [default = "auto"];
  oneof id { int64 uid = 3; string name = 4; }
  optional int32 plain = 5;
}
"""
    desc = compile_proto(src2, str(tmp_path))
    p = DescriptorPool(desc)
    schema = p.message("p2.Legacy")
    out = decode_message(p, schema, encode_message(p, schema, {"name": "x"}))
    assert out["name"] == "x"
    assert "uid" not in out              # unset oneof arm stays null
    assert out["retries"] == 3           # proto2 declared default
    assert out["mode"] == "auto"
    # proto2 `optional` without oneof: presence-tracked too -> absent is null
    assert "plain" not in out or out["plain"] == 0  # (proto2 optional: impl-defined fill)
    # unknown group field skips cleanly: SGROUP(field 9) varint EGROUP
    data = encode_message(p, schema, {"retries": 7}) + b"\x4b\x08\x01\x4c"
    assert decode_message(p, schema, data)["retries"] == 7


def test_enum_and_bytes_defaults_resolved(tmp_path):
    """Review round 3: enum defaults resolve to NUMBERS via the enum
    descriptors; bytes defaults C-unescape; declared group fields stay null."""
    src = """
syntax = "proto2";
package p3;
enum Color { BLUE = 0; RED = 2; }
message M {
  optional Color c = 1 [default = RED];
  optional bytes magic = 2 [default = "\\001\\377A"];
  optional group Legacy = 3 { optional int32 x = 1; }
}
"""
    desc = compile_proto(src, str(tmp_path))
    p = DescriptorPool(desc)
    schema = p.message("p3.M")
    out = decode_message(p, schema, b"")          # everything absent
    assert out["c"] == 2                          # RED -> number
    assert out["magic"] == b"\x01\xffA"           # C-unescaped
    assert "legacy" not in out                    # group stays null
    # a message that SETS the group on the wire: skipped cleanly, others read
    import subprocess
    (tmp_path / "s.proto").write_text(src)
    enc = subprocess.run(
        ["protoc", f"--proto_path={tmp_path}", "--encode=p3.M",
         str(tmp_path / "s.proto")],
        input=b"c: BLUE Legacy { x: 9 }", capture_output=True, check=True)
    out2 = decode_message(p, schema, enc.stdout)
    assert out2["c"] == 0 and "legacy" not in out2


def test_deeply_nested_group_skip_is_iterative():
    """Review round 4: 600 nested group tags (~1.2KB of hostile input) must
    raise ProtoError on truncation, never RecursionError."""
    data = b"\x0b" * 600 + b"\x0c" * 600       # field 1 SGROUP x600, EGROUP x600
    assert list(iter_fields(data)) == []        # fully skipped, no error
    with pytest.raises(ProtoError):             # truncated: missing EGROUPs
        list(iter_fields(b"\x0b" * 600))
