"""Query correctness: differential testing against sqlite3 as oracle.

Reference pattern: `BaseQueriesTest` (pinot-core/src/test/.../queries/BaseQueriesTest.java)
builds real segments and runs the full single-server stack without networking, and the
integration suite checks randomized queries against H2 (`QueryGenerator`). Here sqlite3
(stdlib) is the oracle; the same SQL runs through both engines over identical rows.
"""

import math
import sqlite3

import numpy as np
import pytest

from pinot_tpu.query.executor import execute_query
from pinot_tpu.segment import SegmentBuilder, SegmentGeneratorConfig, load_segment

from conftest import make_ssb_columns


@pytest.fixture(scope="module")
def qenv(tmp_path_factory, ssb_schema):
    """Two segments of SSB data + a sqlite mirror of the union."""
    rng = np.random.default_rng(7)
    out = tmp_path_factory.mktemp("qseg")
    cols_a = make_ssb_columns(rng, 3000)
    cols_b = make_ssb_columns(rng, 2000)
    builder = SegmentBuilder(ssb_schema, SegmentGeneratorConfig(
        inverted_index_columns=["lo_region"]))
    seg_a = load_segment(builder.build(cols_a, str(out), "lineorder_0"))
    seg_b = load_segment(builder.build(cols_b, str(out), "lineorder_1"))

    db = sqlite3.connect(":memory:")
    db.execute("PRAGMA case_sensitive_like=ON")
    names = list(cols_a.keys())
    db.execute(f"CREATE TABLE lineorder ({', '.join(names)})")
    for cols in (cols_a, cols_b):
        rows = list(zip(*[np.asarray(cols[c]).tolist() if isinstance(cols[c], np.ndarray)
                          else cols[c] for c in names]))
        db.executemany(f"INSERT INTO lineorder VALUES ({','.join('?' * len(names))})", rows)
    db.commit()
    return [seg_a, seg_b], db


def run_both(qenv, sql, sqlite_sql=None, ordered=False):
    segments, db = qenv
    ours = execute_query(segments, sql)
    oracle = db.execute(sqlite_sql or sql).fetchall()
    compare(ours.rows, oracle, ordered)
    return ours


def compare(got_rows, want_rows, ordered):
    def norm(rows):
        normed = [tuple(_norm_val(v) for v in r) for r in rows]
        return normed if ordered else sorted(normed, key=repr)
    got, want = norm(got_rows), norm(want_rows)
    assert len(got) == len(want), f"row count {len(got)} != {len(want)}\n{got[:5]}\n{want[:5]}"
    for g, w in zip(got, want):
        assert len(g) == len(w)
        for gv, wv in zip(g, w):
            if isinstance(gv, float) and isinstance(wv, float):
                assert gv == pytest.approx(wv, rel=2e-3, abs=1e-6), f"{g} != {w}"
            else:
                assert gv == wv, f"{g} != {w}"


def _norm_val(v):
    if v is None:
        return None
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, (int, np.integer)):
        return float(v)  # unify int/float across engines
    if isinstance(v, (float, np.floating)):
        return float(v)
    return v


# -- scalar aggregations -----------------------------------------------------

def test_ssb_q1_1(qenv):
    # SSB Q1.1: revenue = SUM(extendedprice * discount) with range filters
    run_both(qenv,
             "SELECT SUM(lo_extendedprice * lo_discount) FROM lineorder "
             "WHERE lo_orderdate BETWEEN 19930101 AND 19931231 "
             "AND lo_discount BETWEEN 1 AND 3 AND lo_quantity < 25 LIMIT 100")


def test_count_star_no_filter(qenv):
    run_both(qenv, "SELECT COUNT(*) FROM lineorder")


def test_min_max_avg(qenv):
    run_both(qenv,
             "SELECT MIN(lo_revenue), MAX(lo_revenue), AVG(lo_quantity), COUNT(*) "
             "FROM lineorder WHERE lo_region = 'ASIA'")


def test_minmaxrange(qenv):
    run_both(qenv,
             "SELECT MINMAXRANGE(lo_quantity) FROM lineorder WHERE lo_region = 'EUROPE'",
             sqlite_sql="SELECT MAX(lo_quantity) - MIN(lo_quantity) FROM lineorder "
                        "WHERE lo_region = 'EUROPE'")


def test_metadata_only_answers(qenv):
    run_both(qenv, "SELECT COUNT(*), MIN(lo_quantity), MAX(lo_revenue) FROM lineorder")


def test_empty_filter_result(qenv):
    run_both(qenv, "SELECT COUNT(*), SUM(lo_revenue) FROM lineorder "
                   "WHERE lo_region = 'ATLANTIS'")


def test_distinctcount(qenv):
    run_both(qenv,
             "SELECT DISTINCTCOUNT(lo_brand) FROM lineorder WHERE lo_quantity > 10",
             sqlite_sql="SELECT COUNT(DISTINCT lo_brand) FROM lineorder "
                        "WHERE lo_quantity > 10")


def test_count_distinct(qenv):
    run_both(qenv,
             "SELECT COUNT(DISTINCT lo_region) FROM lineorder WHERE lo_discount <= 5")


# -- group by ---------------------------------------------------------------

def test_group_by_single(qenv):
    run_both(qenv,
             "SELECT lo_region, SUM(lo_revenue), COUNT(*) FROM lineorder "
             "GROUP BY lo_region LIMIT 100")


def test_group_by_multi_with_filter(qenv):
    run_both(qenv,
             "SELECT lo_region, lo_category, SUM(lo_revenue) FROM lineorder "
             "WHERE lo_quantity BETWEEN 10 AND 40 AND lo_region IN ('ASIA', 'EUROPE') "
             "GROUP BY lo_region, lo_category LIMIT 100")


def test_group_by_order_by_limit(qenv):
    run_both(qenv,
             "SELECT lo_brand, SUM(lo_revenue) AS rev FROM lineorder "
             "GROUP BY lo_brand ORDER BY rev DESC, lo_brand LIMIT 7", ordered=True)


def test_group_by_having(qenv):
    run_both(qenv,
             "SELECT lo_category, COUNT(*) AS c FROM lineorder "
             "GROUP BY lo_category HAVING COUNT(*) > 400 LIMIT 100")


def test_group_by_expression_key(qenv):
    # expression group key -> host fallback path
    run_both(qenv,
             "SELECT lo_discount * 2, COUNT(*) FROM lineorder "
             "GROUP BY lo_discount * 2 LIMIT 100")


def test_group_by_int_column(qenv):
    run_both(qenv,
             "SELECT lo_discount, AVG(lo_extendedprice) FROM lineorder "
             "WHERE lo_category = 'MFGR#3' GROUP BY lo_discount LIMIT 100")


def test_post_aggregation_arithmetic(qenv):
    run_both(qenv,
             "SELECT lo_region, SUM(lo_revenue) / COUNT(*) FROM lineorder "
             "GROUP BY lo_region LIMIT 100",
             sqlite_sql="SELECT lo_region, SUM(lo_revenue) * 1.0 / COUNT(*) "
                        "FROM lineorder GROUP BY lo_region")


def test_order_by_group_key_asc(qenv):
    run_both(qenv,
             "SELECT lo_region, MAX(lo_quantity) FROM lineorder "
             "GROUP BY lo_region ORDER BY lo_region LIMIT 100", ordered=True)


# -- filters ----------------------------------------------------------------

def test_or_not_combinations(qenv):
    run_both(qenv,
             "SELECT COUNT(*) FROM lineorder WHERE "
             "(lo_region = 'ASIA' OR lo_region = 'AFRICA') AND NOT lo_discount = 0")


def test_in_not_in(qenv):
    run_both(qenv,
             "SELECT COUNT(*) FROM lineorder WHERE lo_region IN ('ASIA', 'EUROPE') "
             "AND lo_category NOT IN ('MFGR#1')")


def test_like(qenv):
    run_both(qenv,
             "SELECT COUNT(*) FROM lineorder WHERE lo_brand LIKE 'MFGR#2%'")


def test_neq_and_range_on_string_dict(qenv):
    run_both(qenv,
             "SELECT COUNT(*) FROM lineorder WHERE lo_region != 'ASIA' "
             "AND lo_region > 'AMERICA'")


def test_expression_filter(qenv):
    # arithmetic predicate -> cmp leaf on device
    run_both(qenv,
             "SELECT COUNT(*) FROM lineorder "
             "WHERE lo_extendedprice * lo_quantity > 100000")


def test_float_literal_on_int_column(qenv):
    run_both(qenv, "SELECT COUNT(*) FROM lineorder WHERE lo_quantity > 24.5")
    run_both(qenv, "SELECT COUNT(*) FROM lineorder WHERE lo_quantity = 24.5")


# -- selection --------------------------------------------------------------

def test_selection_order_by(qenv):
    run_both(qenv,
             "SELECT lo_orderkey, lo_region, lo_revenue FROM lineorder "
             "WHERE lo_quantity = 50 ORDER BY lo_revenue DESC, lo_orderkey LIMIT 15",
             ordered=True)


def test_selection_expression(qenv):
    run_both(qenv,
             "SELECT lo_orderkey, lo_extendedprice * (1 - lo_discount) FROM lineorder "
             "WHERE lo_brand = 'MFGR#11' ORDER BY lo_orderkey LIMIT 20", ordered=True)


def test_selection_limit_no_order(qenv):
    segments, db = qenv
    res = execute_query(segments, "SELECT lo_orderkey FROM lineorder LIMIT 5")
    assert len(res.rows) == 5


def test_distinct(qenv):
    run_both(qenv,
             "SELECT DISTINCT lo_region FROM lineorder WHERE lo_discount > 7 LIMIT 100")


def test_default_limit_is_10(qenv):
    segments, _ = qenv
    res = execute_query(segments, "SELECT lo_orderkey FROM lineorder")
    assert len(res.rows) == 10


# -- percentile (vs numpy, sqlite has no percentile) -------------------------

def test_percentile_host_path(qenv):
    segments, db = qenv
    res = execute_query(segments,
                        "SELECT PERCENTILE(lo_quantity, 50) FROM lineorder LIMIT 5")
    vals = [r[0] for r in db.execute("SELECT lo_quantity FROM lineorder")]
    assert res.rows[0][0] == pytest.approx(np.percentile(vals, 50), rel=1e-6)


def test_offset_pagination(qenv):
    segments, _ = qenv
    full = execute_query(segments, "SELECT lo_brand, COUNT(*) FROM lineorder "
                                   "GROUP BY lo_brand ORDER BY lo_brand LIMIT 40")
    page = execute_query(segments, "SELECT lo_brand, COUNT(*) FROM lineorder "
                                   "GROUP BY lo_brand ORDER BY lo_brand LIMIT 10 OFFSET 5")
    assert page.rows == full.rows[5:15]


# -- pruning ----------------------------------------------------------------

def test_minmax_pruning_raw_column(qenv):
    """Range disjoint from metadata min/max folds to an empty plan — no scan."""
    from pinot_tpu.query.context import compile_query
    from pinot_tpu.query.planner import plan_segment
    segments, db = qenv
    ctx = compile_query("SELECT COUNT(*) FROM lineorder WHERE lo_extendedprice > 1e9",
                        segments[0].schema)
    assert plan_segment(ctx, segments[0]).kind == "empty"
    run_both(qenv, "SELECT COUNT(*) FROM lineorder WHERE lo_extendedprice > 1e9")
    # match-all range folds to const-true: becomes a metadata-only count
    ctx2 = compile_query("SELECT COUNT(*) FROM lineorder WHERE lo_extendedprice >= 0",
                         segments[0].schema)
    assert plan_segment(ctx2, segments[0]).kind == "metadata"
    run_both(qenv, "SELECT COUNT(*) FROM lineorder WHERE lo_extendedprice >= 0")


def test_distinctcounthll_device(qenv):
    """HLL estimate within ~3% of exact (device path: one-hot-matmul presence
    vector, registers built host-side from surviving dictionary values)."""
    segments, db = qenv
    from pinot_tpu.query.context import compile_query
    from pinot_tpu.query.planner import plan_segment
    ctx = compile_query("SELECT DISTINCTCOUNTHLL(lo_brand) FROM lineorder "
                        "WHERE lo_quantity > 5", segments[0].schema)
    assert plan_segment(ctx, segments[0]).kind == "device"  # dict column -> device HLL
    res = execute_query(segments, "SELECT DISTINCTCOUNTHLL(lo_custkey) FROM lineorder "
                                  "WHERE lo_quantity > 5")  # raw column -> host HLL
    exact = db.execute("SELECT COUNT(DISTINCT lo_custkey) FROM lineorder "
                       "WHERE lo_quantity > 5").fetchone()[0]
    assert res.rows[0][0] == pytest.approx(exact, rel=0.03)


def test_distinctcounthll_matches_host_path(qenv):
    """Device and host HLL paths produce identical sketches."""
    segments, _ = qenv
    sql = "SELECT DISTINCTCOUNTHLL(lo_brand) FROM lineorder"
    dev = execute_query(segments, sql, use_device=True)
    host = execute_query(segments, sql, use_device=False)
    assert dev.rows == host.rows


# ---------------------------------------------------------------------------
# Device ORDER BY top-k (lax.top_k trim before host materialization)
# ---------------------------------------------------------------------------

class TestDeviceTopK:
    @pytest.fixture(scope="class")
    def seg(self, ssb_segment_dir):
        from pinot_tpu.segment import load_segment
        return load_segment(ssb_segment_dir[0])

    TOPK_QUERIES = [
        "SELECT lo_orderkey, lo_revenue FROM lineorder WHERE lo_quantity < 25 "
        "ORDER BY lo_revenue DESC LIMIT 10",
        "SELECT lo_orderkey, lo_revenue FROM lineorder WHERE lo_quantity < 25 "
        "ORDER BY lo_revenue LIMIT 7",
        "SELECT lo_orderkey FROM lineorder ORDER BY lo_extendedprice DESC LIMIT 5 OFFSET 3",
        "SELECT lo_orderkey FROM lineorder WHERE lo_discount = 10 "
        "ORDER BY lo_extendedprice * lo_discount DESC LIMIT 6",
        # filter matching fewer rows than LIMIT
        "SELECT lo_orderkey FROM lineorder WHERE lo_quantity = 1 AND lo_discount = 0 "
        "ORDER BY lo_revenue DESC LIMIT 5000",
    ]

    @pytest.mark.parametrize("sql", TOPK_QUERIES)
    def test_matches_host_sort(self, seg, sql):
        from pinot_tpu.query.executor import ServerQueryExecutor
        dev = ServerQueryExecutor(use_device=True).execute([seg], sql)
        host = ServerQueryExecutor(use_device=False).execute([seg], sql)
        assert dev.rows == host.rows

    def test_device_trim_is_used(self, seg):
        from pinot_tpu.query.context import compile_query
        from pinot_tpu.query.executor import ServerQueryExecutor
        from pinot_tpu.query.planner import plan_segment
        ctx = compile_query(
            "SELECT lo_orderkey FROM lineorder ORDER BY lo_revenue DESC LIMIT 10",
            seg.schema)
        plan = plan_segment(ctx, seg)
        topk = ServerQueryExecutor()._topk_candidates(plan)
        assert topk is not None
        idx, scanned = topk
        assert scanned == seg.num_docs  # match-all filter
        assert 10 <= len(idx) <= 10 + ServerQueryExecutor.TOPK_SLACK

    def test_wide_int_key_falls_back(self, tmp_path):
        """Integer sort keys beyond 2^24 would misorder in f32 -> exact host sort."""
        import numpy as np
        from pinot_tpu.schema import DataType, Schema, dimension, metric
        from pinot_tpu.segment import SegmentBuilder, load_segment
        from pinot_tpu.query.context import compile_query
        from pinot_tpu.query.executor import ServerQueryExecutor
        from pinot_tpu.query.planner import plan_segment
        schema = Schema("wide", [dimension("id", DataType.LONG),
                                 metric("v", DataType.DOUBLE)])
        rng = np.random.default_rng(53)
        # adjacent wide ints that collide in f32 (2^25 + small deltas)
        ids = (1 << 25) + rng.permutation(64).astype(np.int64)
        seg = load_segment(SegmentBuilder(schema).build(
            {"id": ids, "v": rng.uniform(0, 1, 64)}, str(tmp_path), "wide_0"))
        ctx = compile_query("SELECT id FROM wide ORDER BY id DESC LIMIT 10", schema)
        plan = plan_segment(ctx, seg)
        assert ServerQueryExecutor()._topk_candidates(plan) is None
        dev = ServerQueryExecutor(use_device=True).execute([seg], ctx)
        host = ServerQueryExecutor(use_device=False).execute([seg], ctx)
        assert dev.rows == host.rows

    def test_multisegment_trim_merges(self, ssb_segment_dir, tmp_path, ssb_schema):
        from pinot_tpu.segment import SegmentBuilder, load_segment
        from pinot_tpu.query.executor import ServerQueryExecutor
        import numpy as np
        from conftest import make_ssb_columns
        rng = np.random.default_rng(47)
        segs = [load_segment(ssb_segment_dir[0])]
        cols = make_ssb_columns(rng, 2048)
        segs.append(load_segment(
            SegmentBuilder(ssb_schema).build(cols, str(tmp_path), "lineorder_1")))
        sql = ("SELECT lo_orderkey, lo_revenue FROM lineorder "
               "ORDER BY lo_revenue DESC LIMIT 12")
        dev = ServerQueryExecutor(use_device=True).execute(segs, sql)
        host = ServerQueryExecutor(use_device=False).execute(segs, sql)
        assert dev.rows == host.rows
