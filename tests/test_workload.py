"""Workload intelligence plane: plan fingerprints, shape profiles, sentinel.

The broker normalizes every parsed plan into a 16-hex fingerprint
(sql/fingerprint.py), folds per-query stats into a bounded LRU of per-shape
profiles (cluster/workload.py, served at /debug/workload), and the
controller's WorkloadSentinel burns each shape's over-baseline rate against
the sentinel budget over the shared SLO fast/slow windows — a per-shape
generalization of the per-table SLO machinery in test_table_slo.py.
"""

import threading

import numpy as np
import pytest

from pinot_tpu.cluster.catalog import Catalog
from pinot_tpu.cluster.workload import SLOT_VALUE_CAP, WorkloadRegistry
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.sql.fingerprint import fingerprint_statement
from pinot_tpu.sql.parser import parse_query
from pinot_tpu.table import TableConfig
from pinot_tpu.utils.metrics import get_registry


def _shape(sql):
    return fingerprint_statement(parse_query(sql))


# -- fingerprint normalization ------------------------------------------------

def test_fingerprint_stable_across_literals_whitespace_and_order():
    """Literal values, whitespace/case, AND-conjunct order, and IN-list
    length are NOT part of the shape; each query maps to one fingerprint."""
    base = _shape("SELECT SUM(v) FROM t WHERE a > 5 AND b = 'x' LIMIT 10")
    variants = [
        "SELECT SUM(v) FROM t WHERE a > 99 AND b = 'y' LIMIT 500",
        "select   sum(v)  from t  where a > 5 and b = 'x' limit 10",
        "SELECT SUM(v) FROM t WHERE b = 'x' AND a > 5 LIMIT 10",
    ]
    for sql in variants:
        assert _shape(sql).fingerprint == base.fingerprint, sql
    # slots still capture the literals, in canonical (sorted-conjunct) order
    reordered = _shape(variants[2])
    assert reordered.slots == base.slots

    short = _shape("SELECT a FROM t WHERE a IN (1, 2) LIMIT 5")
    long = _shape("SELECT a FROM t WHERE a IN (7, 8, 9, 10) LIMIT 5")
    assert short.fingerprint == long.fingerprint
    assert short.slots != long.slots   # one variadic slot, different values


def test_fingerprint_distinct_across_plans():
    shapes = [_shape(s) for s in (
        "SELECT SUM(v) FROM t WHERE a > 5 LIMIT 10",
        "SELECT MAX(v) FROM t WHERE a > 5 LIMIT 10",
        "SELECT SUM(v) FROM t WHERE b > 5 LIMIT 10",
        "SELECT SUM(v) FROM t2 WHERE a > 5 LIMIT 10",
        "SELECT a, SUM(v) FROM t WHERE a > 5 GROUP BY a LIMIT 10",
    )]
    fps = {s.fingerprint for s in shapes}
    assert len(fps) == len(shapes)
    assert all(len(fp) == 16 for fp in fps)
    assert shapes[3].tables == ("t2",)


# -- registry: concurrency, LRU eviction, conservation ------------------------

def test_concurrent_registration_exact(tmp_path):
    """8 threads folding into overlapping shapes: every counter exact."""
    reg = WorkloadRegistry(Catalog())
    shapes = [_shape(f"SELECT SUM(v) FROM t WHERE c{i} > 1 LIMIT 5")
              for i in range(4)]
    per_thread = 500

    def worker(tid):
        for i in range(per_thread):
            reg.observe(shapes[(tid + i) % len(shapes)], 1.0,
                        {"numDocsScanned": 10})

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    snap = reg.snapshot()
    assert snap["totalQueries"] == 8 * per_thread
    assert snap["shapesResident"] == len(shapes)
    assert snap["shapesEvicted"] == 0
    counts = {s["fingerprint"]: s["count"] for s in snap["shapes"]}
    assert sum(counts.values()) == 8 * per_thread
    assert all(c == 8 * per_thread // len(shapes) for c in counts.values())
    assert all(s["rowsScanned"] == s["count"] * 10 for s in snap["shapes"])


def test_lru_eviction_overflow_conservation(tmp_path):
    """Over the max.shapes cap the LRU evicts coldest-use shapes, but the
    evicted queries stay counted: nothing is silently truncated."""
    cat = Catalog()
    cat.put_property("clusterConfig/broker.workload.max.shapes", "4")
    reg = WorkloadRegistry(cat)
    base = get_registry().snapshot().get(
        "pinot_broker_workload_shapes_evicted", 0.0)

    shapes = [_shape(f"SELECT SUM(v) FROM t WHERE c{i} > 1 LIMIT 5")
              for i in range(10)]
    for i, s in enumerate(shapes):
        for _ in range(i + 1):   # shape i folded i+1 times
            reg.observe(s, 1.0, {})

    snap = reg.snapshot()
    assert snap["maxShapes"] == 4
    assert snap["shapesResident"] == 4
    assert snap["shapesEvicted"] == 6
    assert snap["shapesSeen"] == 10
    assert snap["shapesEvicted"] + snap["shapesResident"] \
        == snap["shapesSeen"]
    # conservation: resident counts + evicted overflow == every query seen
    total = sum(range(1, 11))
    assert sum(s["count"] for s in snap["shapes"]) \
        + snap["evictedQueries"] == total == snap["totalQueries"]
    assert get_registry().snapshot()[
        "pinot_broker_workload_shapes_evicted"] - base == 6.0
    # most-recently-used survive: shapes 6..9 are the residents
    assert {s["fingerprint"] for s in snap["shapes"]} \
        == {s.fingerprint for s in shapes[6:]}
    # an evicted shape re-admits from scratch (and counts as a new sighting)
    reg.observe(shapes[0], 1.0, {})
    snap = reg.snapshot()
    assert snap["shapesSeen"] == 11 and snap["shapesEvicted"] == 7


def test_slot_cardinality_capped():
    reg = WorkloadRegistry(Catalog())
    for i in range(SLOT_VALUE_CAP + 10):
        reg.observe(_shape(f"SELECT SUM(v) FROM t WHERE a > {i} LIMIT 5"),
                    1.0, {})
    snap = reg.snapshot()
    assert snap["shapesResident"] == 1
    (prof,) = snap["shapes"]
    assert prof["slotOverflowed"] == [True, False]   # a-literal, limit
    assert prof["slotCardinality"][0] <= SLOT_VALUE_CAP + 1
    assert prof["slotCardinality"][1] == 1


# -- segment-version vector (cacheability signal) -----------------------------

def test_segment_versions_bump_on_lifecycle_events():
    cat = Catalog()
    reg = WorkloadRegistry(cat)
    shape = _shape("SELECT SUM(v) FROM trips WHERE a > 1 LIMIT 5")
    reg.observe(shape, 1.0, {})
    assert reg.table_versions() == {}

    # segment commit/upload and ideal-state transitions (evict/demote/
    # relocate) each bump the owning logical table's version
    cat._notify("segment", "trips_OFFLINE")
    cat._notify("segment", "trips_REALTIME")
    cat._notify("ideal_state", "trips_OFFLINE")
    cat._notify("segment", "other_OFFLINE")
    assert reg.table_versions() == {"trips": 3, "other": 1}

    # the next fold of the shape picks up the drift as inputChanges
    reg.observe(shape, 1.0, {})
    prof = reg.shape(shape.fingerprint)
    assert prof["inputChangesSinceFirstSeen"] == 3
    assert prof["segmentVersions"] == {"trips": 3}

    # steady state: no further drift, counter stays put
    reg.observe(shape, 1.0, {})
    assert reg.shape(
        shape.fingerprint)["inputChangesSinceFirstSeen"] == 3


# -- regression sentinel ------------------------------------------------------

@pytest.fixture
def sentinel_controller(tmp_path):
    from pinot_tpu.cluster.controller import Controller
    from pinot_tpu.cluster.deepstore import LocalDeepStore
    catalog = Catalog()
    return Controller("controller_wl", catalog,
                      LocalDeepStore(str(tmp_path / "ds")),
                      str(tmp_path / "ctrl"))


def _wl_poller(shapes):
    return lambda: {"shapes": [dict(s) for s in shapes]}


def test_sentinel_healthy_to_degraded_timeline(sentinel_controller):
    """Synthetic per-shape counter timeline with exact burn arithmetic:
    over-baseline rate vs the 1% budget over fast AND slow windows."""
    c = sentinel_controller
    fp = "deadbeef00c0ffee"
    shape = {"fingerprint": fp, "canonical": "select sum(v); from t",
             "tables": ["t"], "count": 1000, "overBaseline": 0,
             "totalTimeMs": 5000.0, "baselineMs": 5.0}
    c.workload_pollers["b1"] = _wl_poller([shape])

    # first observation: single sample in every window -> zero burn
    assert c.run_workload_check(now=1000.0) == {fp: "HEALTHY"}
    st = c.workload_status()
    assert st["state"] == "HEALTHY" and st["shapesTracked"] == 1
    base_regr = get_registry().snapshot().get(
        "pinot_broker_workload_shape_regressions", 0.0)

    # 5 violators over 1000 queries = 0.5% < the 1% budget -> HEALTHY
    shape.update(count=2000, overBaseline=5)
    assert c.run_workload_check(now=1060.0) == {fp: "HEALTHY"}

    # window delta vs the ts=1000 sample: 2000 queries, 25 over-baseline
    # = 1.25% -> 1.25x budget in BOTH windows -> DEGRADED, reason names
    # the offending fingerprint
    shape.update(count=3000, overBaseline=25)
    assert c.run_workload_check(now=1120.0) == {fp: "DEGRADED"}
    st = c.workload_status()
    assert st["state"] == "DEGRADED"
    reg_entry = st["regressions"][fp]
    assert reg_entry["burnFast"] == 1.25 and reg_entry["burnSlow"] == 1.25
    assert fp in reg_entry["reason"] and "1.25x fast" in reg_entry["reason"]
    snap = get_registry().snapshot()
    assert snap["pinot_broker_workload_shape_regressions"] - base_regr == 1.0
    assert snap["pinot_controller_workload_regressing_shapes"] == 1.0

    # still regressing next tick: the transition counter does NOT re-tick
    shape.update(count=4000, overBaseline=55)
    assert c.run_workload_check(now=1180.0) == {fp: "DEGRADED"}
    assert get_registry().snapshot()[
        "pinot_broker_workload_shape_regressions"] - base_regr == 1.0

    # 800 over 4000 in-window queries = 20% = 20x >= the 14.4x page rate
    shape.update(count=5000, overBaseline=800)
    assert c.run_workload_check(now=1240.0) == {fp: "UNHEALTHY"}
    assert c.workload_status()["state"] == "UNHEALTHY"

    # recovery: clean traffic drains the burn back under budget
    # (800 violators over 99000 in-window queries = 0.81%)
    shape.update(count=100000, overBaseline=800)
    assert c.run_workload_check(now=1300.0) == {fp: "HEALTHY"}
    assert c.workload_status()["state"] == "HEALTHY"
    assert get_registry().snapshot()[
        "pinot_controller_workload_regressing_shapes"] == 0.0


def test_sentinel_disable_and_stale_shape_pruning(sentinel_controller):
    c = sentinel_controller
    shape = {"fingerprint": "aa" * 8, "canonical": "x", "tables": [],
             "count": 100, "overBaseline": 0, "totalTimeMs": 1.0,
             "baselineMs": 1.0}
    c.workload_pollers["b1"] = _wl_poller([shape])
    c.run_workload_check(now=2000.0)
    assert c.workload_status()["shapesTracked"] == 1

    # shape evicted broker-side: its sample history is pruned
    c.workload_pollers["b1"] = _wl_poller([])
    c.run_workload_check(now=2060.0)
    assert c.workload_status()["shapesTracked"] == 0
    assert not c._workload_samples

    # budget <= 0 disables the sentinel and tears the plane down
    c.catalog.put_property("clusterConfig/workload.sentinel.budget", "0")
    assert c.run_workload_check(now=2120.0) == {}
    assert c.workload_status() == {}
    assert "pinot_controller_workload_regressing_shapes" \
        not in get_registry().snapshot()


def test_sentinel_unreachable_broker_reported(sentinel_controller):
    c = sentinel_controller

    def boom():
        raise OSError("connection refused")

    c.workload_pollers["b1"] = boom
    c.run_workload_check(now=3000.0)
    assert c.workload_status()["unreachableBrokers"] == ["b1"]


# -- end to end: zipf mix through a real cluster ------------------------------

def test_workload_debug_endpoint_zipf_mix(tmp_path):
    """Acceptance demo: a zipf mix over 20+ distinct shapes, served queries
    carrying their fingerprint, /debug/workload top-K + drill-down, and
    conservation across the whole run."""
    from pinot_tpu.cluster import QuickCluster

    schema = Schema("wl", [dimension("site", DataType.STRING),
                           metric("v", DataType.LONG),
                           metric("w", DataType.LONG)])
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    cfg = TableConfig("wl", replication=1)
    cluster.create_table(schema, cfg)
    cluster.ingest_columns(cfg, {
        "site": np.array(["a", "b", "c", "d"] * 64),
        "v": np.arange(256, dtype=np.int64),
        "w": np.arange(256, dtype=np.int64) % 7,
    })
    cluster.catalog.put_property("clusterConfig/broker.slow.query.ms", "0")

    # 5 aggs x 2 columns x 2 predicate structures = 20 structurally
    # distinct plans (a template varying only its literals is ONE shape)
    aggs = ["COUNT(*)", "SUM(v)", "MAX(v)", "MIN(w)", "SUM(w)"]
    cols = ["v", "w"]
    forms = ["WHERE {c} > {{n}}", "WHERE {c} > {{n}} AND site = 'a'"]
    templates = [f"SELECT {a} FROM wl " + f.format(c=c) + " LIMIT 10"
                 for a in aggs for c in cols for f in forms]
    assert len(templates) >= 20

    rng = np.random.default_rng(11)
    ranks = np.concatenate([
        np.arange(len(templates)),
        np.minimum(rng.zipf(1.4, size=40) - 1, len(templates) - 1)])
    reg = cluster.broker.workload
    base_total = reg.snapshot()["totalQueries"]
    fps = {}
    for r in ranks:
        res = cluster.query(templates[int(r)].format(
            n=int(rng.integers(0, 100))))
        fp = res.stats.get("workloadFingerprint")
        assert fp, "served query must carry its fingerprint"
        fps.setdefault(int(r), set()).add(fp)

    # literal-varied queries of one template landed in ONE shape each,
    # distinct templates in distinct shapes
    assert all(len(s) == 1 for s in fps.values())
    assert len({next(iter(s)) for s in fps.values()}) == len(templates)

    snap = reg.snapshot()
    assert snap["totalQueries"] - base_total == len(ranks)
    assert sum(s["count"] for s in snap["shapes"]) \
        + snap["evictedQueries"] == snap["totalQueries"]
    assert abs(sum(s["timeSharePct"] for s in snap["shapes"]) - 100.0) < 1.0

    # top-K trims the ranking but not the conservation counters
    top = reg.snapshot(k=5)
    assert len(top["shapes"]) == 5
    assert top["totalQueries"] == snap["totalQueries"]
    assert [s["totalTimeMs"] for s in top["shapes"]] \
        == sorted((s["totalTimeMs"] for s in top["shapes"]), reverse=True)

    # drill-down resolves; the slow log line joins on the same fingerprint
    hot = top["shapes"][0]["fingerprint"]
    detail = reg.shape(hot)
    assert detail["fingerprint"] == hot and "slotValues" in detail
    assert reg.shape("0" * 16) is None
    slow_fp = cluster.broker._recent_slow[-1]["workloadFingerprint"]
    assert any(slow_fp in s for s in fps.values())

    # the broker's main /debug body carries the light rollup
    summary = cluster.broker.debug_stats()["workload"]
    assert summary["totalQueries"] == snap["totalQueries"]
