"""Aggregation breadth: moments, covariance/correlation, first/last-with-time,
histogram, distinct-sum/avg, boolean aggs, exact decimal sum, raw t-digest.

Reference: AggregationFunctionType.java:31-80 — VarianceAggregationFunction,
CovarianceAggregationFunction, LastWithTimeAggregationFunction,
HistogramAggregationFunction, SumPrecisionAggregationFunction, etc.
"""

import numpy as np
import pytest

from pinot_tpu.query.executor import ServerQueryExecutor, execute_query
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.segment.reader import load_segment
from pinot_tpu.segment.writer import SegmentBuilder, SegmentGeneratorConfig

N = 500
RNG = np.random.default_rng(7)
X = np.round(RNG.normal(50, 10, N), 3)
Y = np.round(X * 0.5 + RNG.normal(0, 5, N), 3)
T = RNG.permutation(N).astype(np.int64)
FLAG = (RNG.random(N) < 0.8).astype(np.int32)
GROUP = np.array([["a", "b", "c"][i % 3] for i in range(N)], dtype=object)

SCHEMA = Schema("stats", [
    dimension("g", DataType.STRING),
    metric("x", DataType.DOUBLE),
    metric("y", DataType.DOUBLE),
    metric("t", DataType.LONG),
    metric("flag", DataType.BOOLEAN),
    metric("small", DataType.INT),
])
COLS = {"g": GROUP, "x": X, "y": Y, "t": T, "flag": FLAG,
        "small": (np.arange(N) % 7).astype(np.int32)}


@pytest.fixture(scope="module")
def seg(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("stats")
    return load_segment(SegmentBuilder(SCHEMA, SegmentGeneratorConfig())
                        .build(dict(COLS), str(tmp), "stats_0"))


def one(seg, sql, use_device=True):
    return ServerQueryExecutor(use_device=use_device).execute([seg], sql).rows[0]


# -- variance family ----------------------------------------------------------

def test_variance_family(seg):
    row = one(seg, "SELECT VAR_POP(x), VAR_SAMP(x), STDDEV_POP(x), STDDEV_SAMP(x) "
                   "FROM stats", use_device=False)
    assert row[0] == pytest.approx(np.var(X), rel=1e-9)
    assert row[1] == pytest.approx(np.var(X, ddof=1), rel=1e-9)
    assert row[2] == pytest.approx(np.std(X), rel=1e-9)
    assert row[3] == pytest.approx(np.std(X, ddof=1), rel=1e-9)


def test_variance_device_path(seg):
    # device computes f32 power sums; the estimate must be close, and the plan
    # must actually be the device one
    from pinot_tpu.query.planner import plan_segment
    from pinot_tpu.query.context import compile_query
    ctx = compile_query("SELECT VAR_POP(x) FROM stats", seg.schema)
    assert plan_segment(ctx, seg).kind == "device"
    row = one(seg, "SELECT VAR_POP(x) FROM stats", use_device=True)
    assert row[0] == pytest.approx(np.var(X), rel=2e-2)


def test_variance_group_by_merges(seg):
    res = execute_query(
        [seg], "SELECT g, VAR_POP(x) FROM stats GROUP BY g ORDER BY g LIMIT 5")
    for g, var in res.rows:
        assert var == pytest.approx(np.var(X[GROUP == g]), rel=2e-2)


def test_variance_cross_segment_merge(tmp_path):
    """Power-sum states must merge exactly across segments."""
    half = N // 2
    segs = []
    for i, sl in enumerate([slice(0, half), slice(half, N)]):
        cols = {k: v[sl] for k, v in COLS.items()}
        segs.append(load_segment(SegmentBuilder(SCHEMA, SegmentGeneratorConfig())
                                 .build(cols, str(tmp_path), f"s_{i}")))
    row = ServerQueryExecutor(use_device=False).execute(
        segs, "SELECT STDDEV_SAMP(x) FROM stats").rows[0]
    assert row[0] == pytest.approx(np.std(X, ddof=1), rel=1e-9)


def test_skewness_kurtosis(seg):
    row = one(seg, "SELECT SKEWNESS(x), KURTOSIS(x) FROM stats", use_device=False)
    m = X - X.mean()
    skew = (m ** 3).mean() / (m ** 2).mean() ** 1.5
    kurt = (m ** 4).mean() / (m ** 2).mean() ** 2 - 3
    assert row[0] == pytest.approx(skew, abs=1e-9)
    assert row[1] == pytest.approx(kurt, abs=1e-9)


# -- two-argument -------------------------------------------------------------

def test_covariance_and_corr(seg):
    row = one(seg, "SELECT COVAR_POP(x, y), COVAR_SAMP(x, y), CORR(x, y) "
                   "FROM stats", use_device=False)
    assert row[0] == pytest.approx(np.cov(X, Y, bias=True)[0, 1], rel=1e-9)
    assert row[1] == pytest.approx(np.cov(X, Y)[0, 1], rel=1e-9)
    assert row[2] == pytest.approx(np.corrcoef(X, Y)[0, 1], rel=1e-9)


def test_covar_group_by(seg):
    res = execute_query(
        [seg], "SELECT g, COVAR_POP(x, y) FROM stats GROUP BY g ORDER BY g LIMIT 5")
    for g, c in res.rows:
        m = GROUP == g
        assert c == pytest.approx(np.cov(X[m], Y[m], bias=True)[0, 1], rel=1e-9)


def test_first_last_with_time(seg):
    row = one(seg, "SELECT FIRSTWITHTIME(x, t, 'DOUBLE'), "
                   "LASTWITHTIME(x, t, 'DOUBLE') FROM stats", use_device=False)
    assert row[0] == pytest.approx(X[np.argmin(T)])
    assert row[1] == pytest.approx(X[np.argmax(T)])


def test_last_with_time_filtered(seg):
    row = one(seg, "SELECT LASTWITHTIME(x, t, 'DOUBLE') FROM stats WHERE x < 50",
              use_device=False)
    m = X < 50
    assert row[0] == pytest.approx(X[m][np.argmax(T[m])])


# -- histogram / distinct / bool / decimal ------------------------------------

def test_histogram(seg):
    row = one(seg, "SELECT HISTOGRAM(x, 20, 80, 6) FROM stats", use_device=False)
    idx = np.clip(np.floor((X - 20) / 60 * 6), 0, 5).astype(int)
    expected = np.bincount(idx, minlength=6).tolist()
    assert row[0] == expected
    assert sum(row[0]) == N


def test_distinct_sum_avg(seg):
    row = one(seg, "SELECT DISTINCTSUM(small), DISTINCTAVG(small) FROM stats")
    assert row[0] == pytest.approx(sum(range(7)))
    assert row[1] == pytest.approx(np.mean(range(7)))


def test_bool_and_or(seg):
    row = one(seg, "SELECT BOOL_AND(flag), BOOL_OR(flag) FROM stats")
    assert row[0] == bool(FLAG.all())
    assert row[1] == bool(FLAG.any())
    row = one(seg, "SELECT BOOL_AND(flag) FROM stats WHERE flag = 1")
    assert row[0] is True


def test_sumprecision():
    import tempfile
    with tempfile.TemporaryDirectory() as tmp:
        schema = Schema("d", [metric("v", DataType.DOUBLE)])
        seg = load_segment(SegmentBuilder(schema).build(
            {"v": np.array([0.1, 0.2, 0.3])}, tmp, "d_0"))
        row = one(seg, "SELECT SUMPRECISION(v) FROM d", use_device=False)
        assert row[0] == "0.6"   # exact decimal, no float drift


def test_percentile_raw_tdigest(seg):
    from pinot_tpu.query.sketches import TDigest
    row = one(seg, "SELECT PERCENTILERAWTDIGEST50(x) FROM stats", use_device=False)
    td = TDigest.from_bytes(bytes.fromhex(row[0]))
    assert td.quantile(0.5) == pytest.approx(np.median(X), rel=0.05)


# -- validation + numeric-safety guards ---------------------------------------

def test_large_magnitude_moments_take_host_path(tmp_path):
    """f32 power sums would cancel catastrophically on epoch-sized values; the
    planner must route such columns to the f64 host path — and the answer must
    be exact."""
    from pinot_tpu.query.context import compile_query
    from pinot_tpu.query.planner import plan_segment
    ts = np.float64(1.7e9) + np.arange(1000, dtype=np.float64)  # epoch seconds
    schema = Schema("big", [metric("ts", DataType.DOUBLE)])
    seg = load_segment(SegmentBuilder(schema).build(
        {"ts": ts}, str(tmp_path), "big_0"))
    ctx = compile_query("SELECT VAR_POP(ts) FROM big", schema)
    assert plan_segment(ctx, seg).kind == "host"
    row = one(seg, "SELECT VAR_POP(ts) FROM big")
    assert row[0] == pytest.approx(np.var(ts), rel=1e-9)


def test_agg_arg_type_validation(seg):
    from pinot_tpu.query.context import QueryValidationError
    with pytest.raises(QueryValidationError, match="BOOLEAN"):
        one(seg, "SELECT BOOL_AND(x) FROM stats")        # DOUBLE column
    with pytest.raises(QueryValidationError, match="numeric"):
        one(seg, "SELECT DISTINCTSUM(g) FROM stats")     # STRING column
    with pytest.raises(QueryValidationError, match="numeric"):
        one(seg, "SELECT LASTWITHTIME(g, t, 'STRING') FROM stats")
    with pytest.raises(QueryValidationError, match="numeric"):
        one(seg, "SELECT VAR_POP(g) FROM stats")


def test_sumprecision_empty_is_null(seg):
    row = one(seg, "SELECT SUMPRECISION(x), SUM(x) FROM stats WHERE x > 1e9",
              use_device=False)
    assert row[0] is None and row[1] is None


# -- device/host parity over the new device-capable functions -----------------

@pytest.mark.parametrize("sql", [
    "SELECT VAR_POP(x) FROM stats WHERE x > 40",
    "SELECT g, STDDEV_POP(y) FROM stats GROUP BY g LIMIT 5",
    "SELECT BOOL_OR(flag), COUNT(*) FROM stats WHERE x > 60",
    "SELECT DISTINCTSUM(small) FROM stats WHERE g = 'a'",
])
def test_device_host_parity(seg, sql):
    dev = ServerQueryExecutor(use_device=True).execute([seg], sql).rows
    host = ServerQueryExecutor(use_device=False).execute([seg], sql).rows

    def close(a, b):
        if isinstance(a, float) and isinstance(b, float):
            return a == pytest.approx(b, rel=2e-2)
        return a == b
    assert len(dev) == len(host)
    for ra, rb in zip(sorted(map(str, dev)), sorted(map(str, host))):
        pass  # order-insensitive structural check below
    for ra, rb in zip(dev, host):
        assert all(close(a, b) for a, b in zip(ra, rb)), (dev, host)


def test_segment_partitioned_distinct_count(tmp_path):
    """Per-segment exact distinct summed across segments — exact when segments
    hold disjoint value ranges (reference: SegmentPartitionedDistinctCount)."""
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    schema = Schema("p", [dimension("k", DataType.STRING), metric("v", DataType.INT)])
    b = SegmentBuilder(schema)
    s1 = load_segment(b.build({"k": ["a", "b", "a"], "v": np.array([1, 2, 3], dtype=np.int32)},
                              str(tmp_path), "p_0"))
    s2 = load_segment(b.build({"k": ["c", "d"], "v": np.array([4, 5], dtype=np.int32)},
                              str(tmp_path), "p_1"))
    res = execute_query([s1, s2],
                        "SELECT SEGMENTPARTITIONEDDISTINCTCOUNT(k) FROM p")
    assert res.rows[0][0] == 4  # 2 + 2, disjoint -> exact


def test_distinctcount_smart_hll(seg):
    exact = execute_query([seg], "SELECT DISTINCTCOUNT(small) FROM stats").rows[0][0]
    smart = execute_query([seg],
                          "SELECT DISTINCTCOUNTSMARTHLL(small) FROM stats").rows[0][0]
    assert smart == exact  # under threshold: exact set path
    # force the HLL degrade with a tiny threshold; estimate within 15%
    approx = execute_query(
        [seg], "SELECT DISTINCTCOUNTSMARTHLL(small, 2) FROM stats").rows[0][0]
    assert approx == pytest.approx(exact, rel=0.2)


def test_raw_hll_and_aliases(seg):
    import numpy as np
    from pinot_tpu.query.aggregates import HLL_DEFAULT_P, hll_estimate
    raw = execute_query([seg], "SELECT DISTINCTCOUNTRAWHLL(small) FROM stats"
                        ).rows[0][0]
    regs = np.frombuffer(bytes.fromhex(raw), dtype=np.int8)
    assert len(regs) == 1 << HLL_DEFAULT_P
    est = hll_estimate(regs)
    exact = execute_query([seg], "SELECT DISTINCTCOUNT(small) FROM stats").rows[0][0]
    assert est == pytest.approx(exact, rel=0.2)
    # FASTHLL legacy alias behaves like DISTINCTCOUNTHLL
    a = execute_query([seg], "SELECT FASTHLL(small) FROM stats").rows[0][0]
    b = execute_query([seg], "SELECT DISTINCTCOUNTHLL(small) FROM stats").rows[0][0]
    assert a == b


def test_percentile_smart_tdigest(seg):
    exact = execute_query([seg], "SELECT PERCENTILE(x, 90) FROM stats").rows[0][0]
    smart = execute_query([seg],
                          "SELECT PERCENTILESMARTTDIGEST(x, 90) FROM stats").rows[0][0]
    assert smart == pytest.approx(exact, rel=1e-9)  # under threshold: exact
    degraded = execute_query(
        [seg], "SELECT PERCENTILESMARTTDIGEST(x, 90, 'threshold=10') FROM stats"
    ).rows[0][0]
    assert degraded == pytest.approx(exact, rel=0.1)


def test_percentile_rawest(seg):
    from pinot_tpu.query.sketches import TDigest
    raw = execute_query([seg], "SELECT PERCENTILERAWEST90(t) FROM stats").rows[0][0]
    d = TDigest.from_bytes(bytes.fromhex(raw))
    exact = execute_query([seg], "SELECT PERCENTILEEST(t, 90) FROM stats").rows[0][0]
    assert d.quantile(0.9) == pytest.approx(exact, rel=0.05)


def test_percentile_smart_tdigest_suffix_form_threshold(seg):
    exact = execute_query([seg], "SELECT PERCENTILE(x, 90) FROM stats").rows[0][0]
    got = execute_query(
        [seg], "SELECT PERCENTILESMARTTDIGEST90(x, 'threshold=10') FROM stats"
    ).rows[0][0]
    assert got == pytest.approx(exact, rel=0.1)
    from pinot_tpu.query.aggregates import make_agg
    from pinot_tpu.sql.ast import Function, Identifier, Literal
    agg = make_agg(Function("percentilesmarttdigest90",
                            (Identifier("x"), Literal("threshold=10"))))
    assert agg.threshold == 10 and agg.pct == 90.0
