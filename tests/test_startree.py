"""Star-tree index: build correctness + query-rewrite equivalence.

Reference pattern: `StarTreeV2BuilderTest` + star-tree query suites compare star-tree
answers against the scan path over the same data. Here every fitting query must return
bit-identical group keys and numerically-equal aggregates with and without the tree,
and must scan fewer (pre-aggregated) records.
"""

import numpy as np
import pytest

from pinot_tpu.query.context import compile_query
from pinot_tpu.query.executor import ServerQueryExecutor, execute_query
from pinot_tpu.query.startree_exec import try_star_tree
from pinot_tpu.segment import (SegmentBuilder, SegmentGeneratorConfig,
                               StarTreeIndexConfig, load_segment)
from pinot_tpu.schema import DataType, Schema, dimension, metric

from conftest import make_ssb_columns


@pytest.fixture(scope="module")
def st_env(tmp_path_factory, ssb_schema):
    """The same data built twice: with star-trees and without (the oracle)."""
    rng = np.random.default_rng(11)
    out = tmp_path_factory.mktemp("st")
    cols = make_ssb_columns(rng, 5000)
    st_cfg = StarTreeIndexConfig(
        dimensions_split_order=["lo_region", "lo_category", "lo_discount"],
        function_column_pairs=["SUM__lo_revenue", "AVG__lo_quantity",
                               "MIN__lo_extendedprice", "MAX__lo_extendedprice",
                               "MINMAXRANGE__lo_extendedprice"],
        max_leaf_records=10,
    )
    with_tree = load_segment(SegmentBuilder(ssb_schema, SegmentGeneratorConfig(
        star_tree_configs=[st_cfg])).build(cols, str(out), "st_seg"))
    plain = load_segment(SegmentBuilder(ssb_schema).build(cols, str(out), "plain_seg"))
    return with_tree, plain


FITTING_QUERIES = [
    "SELECT lo_region, SUM(lo_revenue) FROM lineorder GROUP BY lo_region",
    "SELECT lo_region, lo_category, SUM(lo_revenue), COUNT(*) FROM lineorder "
    "GROUP BY lo_region, lo_category",
    "SELECT SUM(lo_revenue), COUNT(*) FROM lineorder WHERE lo_region = 'ASIA'",
    "SELECT lo_category, AVG(lo_quantity) FROM lineorder "
    "WHERE lo_region IN ('ASIA', 'EUROPE') GROUP BY lo_category",
    "SELECT lo_region, MIN(lo_extendedprice), MAX(lo_extendedprice) FROM lineorder "
    "WHERE lo_discount BETWEEN 2 AND 7 GROUP BY lo_region",
    "SELECT MINMAXRANGE(lo_extendedprice) FROM lineorder WHERE lo_category = 'MFGR#2'",
    "SELECT lo_discount, COUNT(*) FROM lineorder WHERE lo_region <> 'AFRICA' "
    "GROUP BY lo_discount",
    # OR across dimensions: no child pruning, but still answerable from the tree
    "SELECT COUNT(*) FROM lineorder WHERE lo_region = 'ASIA' OR lo_category = 'MFGR#1'",
]


def _rows_match(a, b):
    sa = sorted([tuple(r) for r in a], key=repr)
    sb = sorted([tuple(r) for r in b], key=repr)
    assert len(sa) == len(sb), f"{len(sa)} != {len(sb)}"
    for ra, rb in zip(sa, sb):
        assert len(ra) == len(rb)
        for va, vb in zip(ra, rb):
            if isinstance(va, float) or isinstance(vb, float):
                assert va == pytest.approx(vb, rel=1e-4, abs=1e-4)
            else:
                assert va == vb


@pytest.mark.parametrize("sql", FITTING_QUERIES)
def test_startree_matches_scan(st_env, sql):
    with_tree, plain = st_env
    got = execute_query([with_tree], sql)
    want = execute_query([plain], sql)
    _rows_match(got.rows, want.rows)
    # the tree must actually be used and must scan fewer records than raw docs
    assert got.stats["numDocsScanned"] < want.stats["numDocsScanned"]


def test_fit_detection(st_env):
    with_tree, plain = st_env
    sch = with_tree.schema
    fit = compile_query(
        "SELECT lo_region, SUM(lo_revenue) FROM lineorder GROUP BY lo_region", sch)
    assert try_star_tree(fit, with_tree) is not None
    assert try_star_tree(fit, plain) is None
    # group-by on a non-tree dimension: no fit
    nofit = compile_query(
        "SELECT lo_brand, SUM(lo_revenue) FROM lineorder GROUP BY lo_brand", sch)
    assert try_star_tree(nofit, with_tree) is None
    # unsupported aggregation: no fit
    nofit2 = compile_query(
        "SELECT lo_region, DISTINCTCOUNT(lo_custkey) FROM lineorder GROUP BY lo_region",
        sch)
    assert try_star_tree(nofit2, with_tree) is None
    # filter on a non-tree column: no fit
    nofit3 = compile_query(
        "SELECT SUM(lo_revenue) FROM lineorder WHERE lo_quantity > 10", sch)
    assert try_star_tree(nofit3, with_tree) is None


def test_non_fitting_queries_still_correct(st_env):
    """Queries that miss the tree fall back to the scan path transparently."""
    with_tree, plain = st_env
    for sql in [
        "SELECT lo_brand, SUM(lo_revenue) FROM lineorder GROUP BY lo_brand",
        "SELECT SUM(lo_revenue) FROM lineorder WHERE lo_quantity > 25",
        "SELECT DISTINCTCOUNT(lo_region) FROM lineorder",
    ]:
        got = execute_query([with_tree], sql)
        want = execute_query([plain], sql)
        _rows_match(got.rows, want.rows)


def test_startree_mixed_segments(st_env):
    """A query over one star-tree segment and one plain segment merges correctly."""
    with_tree, plain = st_env
    sql = ("SELECT lo_region, SUM(lo_revenue), COUNT(*), AVG(lo_quantity) "
           "FROM lineorder GROUP BY lo_region")
    got = execute_query([with_tree, plain], sql)
    want = execute_query([plain, plain], sql)
    _rows_match(got.rows, want.rows)


def test_host_path_matches_device(st_env):
    with_tree, _ = st_env
    sql = ("SELECT lo_region, SUM(lo_revenue) FROM lineorder "
           "WHERE lo_discount <= 5 GROUP BY lo_region")
    dev = ServerQueryExecutor(use_device=True).execute([with_tree], sql)
    host = ServerQueryExecutor(use_device=False).execute([with_tree], sql)
    _rows_match(dev.rows, host.rows)


def test_tiny_and_skip_star_configs(tmp_path):
    """max_leaf_records=1 (fully split tree) and skipped star dimensions."""
    schema = Schema("t", [dimension("d1", DataType.STRING),
                          dimension("d2", DataType.INT),
                          metric("m", DataType.DOUBLE)])
    rng = np.random.default_rng(3)
    n = 400
    cols = {
        "d1": [f"k{i}" for i in rng.integers(0, 7, n)],
        "d2": rng.integers(0, 5, n).astype(np.int32),
        "m": rng.uniform(0, 100, n),
    }
    cfg = SegmentGeneratorConfig(star_tree_configs=[StarTreeIndexConfig(
        dimensions_split_order=["d1", "d2"],
        function_column_pairs=["SUM__m"],
        max_leaf_records=1,
        skip_star_node_creation=["d2"],
    )])
    seg = load_segment(SegmentBuilder(schema, cfg).build(cols, str(tmp_path), "s1"))
    plain = load_segment(SegmentBuilder(schema).build(cols, str(tmp_path), "s2"))
    for sql in [
        "SELECT d1, SUM(m) FROM t GROUP BY d1",
        "SELECT d2, SUM(m), COUNT(*) FROM t GROUP BY d2",
        "SELECT SUM(m) FROM t WHERE d1 = 'k3'",
        "SELECT COUNT(*) FROM t WHERE d2 >= 2",
    ]:
        _rows_match(execute_query([seg], sql).rows, execute_query([plain], sql).rows)


def test_startree_randomized_differential(st_env):
    """Randomized queries over the tree's dimension/metric domain: the star
    tree rewrite must agree with the plain scan on EVERY shape (filters on any
    split dims, any key subset, all covered aggregations)."""
    import numpy as np
    with_tree, plain = st_env
    rng = np.random.default_rng(314)
    dims = ["lo_region", "lo_category", "lo_discount"]
    aggs = ["SUM(lo_revenue)", "AVG(lo_quantity)", "MIN(lo_extendedprice)",
            "MAX(lo_extendedprice)", "COUNT(*)"]
    regions = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
    cats = [f"MFGR#{i}" for i in range(1, 6)]
    used_tree = 0
    for qi in range(40):
        keys = [d for d in dims if rng.random() < 0.5]
        chosen = list(dict.fromkeys(
            aggs[rng.integers(0, len(aggs))] for _ in range(int(rng.integers(1, 4)))))
        preds = []
        if rng.random() < 0.6:
            vals = ", ".join(f"'{regions[i]}'" for i in
                             sorted(set(rng.integers(0, 5, int(rng.integers(1, 3)))))) 
            preds.append(f"lo_region IN ({vals})")
        if rng.random() < 0.4:
            preds.append(f"lo_category = '{cats[rng.integers(0, 5)]}'")
        if rng.random() < 0.4:
            preds.append(f"lo_discount BETWEEN {int(rng.integers(0, 5))} "
                         f"AND {int(rng.integers(5, 11))}")
        where = (" WHERE " + " AND ".join(preds)) if preds else ""
        select = ", ".join(keys + chosen)
        group = f" GROUP BY {', '.join(keys)}" if keys else ""
        sql = f"SELECT {select} FROM lineorder{where}{group} LIMIT 100000"
        got = execute_query([with_tree], sql)
        want = execute_query([plain], sql)
        _rows_match(got.rows, want.rows)
        if got.stats["numDocsScanned"] < want.stats["numDocsScanned"]:
            used_tree += 1
    assert used_tree >= 30, f"tree used only {used_tree}/40 times"


def test_stacked_device_star_path_high_cardinality(tmp_path):
    """r4 (BASELINE config 3 as designed): segments whose star-trees have
    LARGE record tables run the stacked device path — record tables stack
    like base segments, split-dim predicates fuse into the kernel mask, and
    per-segment traversal masks ride the valid input. Results must equal the
    per-segment host star path exactly."""
    import numpy as np
    from pinot_tpu.parallel import MeshQueryExecutor, default_mesh
    from pinot_tpu.parallel.combine import StarSetPlan
    from pinot_tpu.query.context import compile_query
    from pinot_tpu.query.executor import ServerQueryExecutor
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.segment import (SegmentGeneratorConfig, StarTreeIndexConfig,
                                   load_segment)
    from pinot_tpu.segment.writer import build_aligned_segments

    rng = np.random.default_rng(17)
    n = 120_000
    schema = Schema("hc", [
        dimension("d1", DataType.INT), dimension("d2", DataType.INT),
        metric("m", DataType.DOUBLE)])
    cols = {"d1": rng.integers(0, 300, n).astype(np.int32),
            "d2": rng.integers(0, 300, n).astype(np.int32),
            "m": np.round(rng.uniform(0, 100, n), 3)}
    cfg = SegmentGeneratorConfig(star_tree_configs=[StarTreeIndexConfig(
        dimensions_split_order=["d1", "d2"],
        function_column_pairs=["SUM__m", "COUNT__*"])])
    paths = build_aligned_segments(schema, cols, str(tmp_path), "hc", 2,
                                   config=cfg)
    segs = [load_segment(p) for p in paths]
    total_records = sum(t.num_records for s in segs for t in s.star_trees)
    assert total_records >= 65536, total_records   # large-table premise

    mesh_exec = MeshQueryExecutor(default_mesh(8))
    sql = ("SELECT d1, SUM(m), COUNT(*) FROM hc WHERE d2 < 120 "
           "GROUP BY d1 ORDER BY d1 LIMIT 1000")
    ctx = compile_query(sql, schema)
    sp = mesh_exec._plan_star_device(ctx, segs)
    assert isinstance(sp, StarSetPlan), "stacked star path must plan"

    sharded = mesh_exec.execute(segs, sql)
    host = ServerQueryExecutor().execute(segs, sql)       # host star path
    assert [r[0] for r in sharded.rows] == [r[0] for r in host.rows]
    for a, b in zip(sharded.rows, host.rows):
        assert a[2] == b[2]                               # counts exact
        assert a[1] == pytest.approx(b[1], rel=1e-6)
    # truth from the raw columns
    want = {}
    m_ok = cols["d2"] < 120
    for d1 in np.unique(cols["d1"]):
        mm = m_ok & (cols["d1"] == d1)
        want[int(d1)] = (float(cols["m"][mm].sum()), int(mm.sum()))
    for d1, s, c in sharded.rows:
        assert c == want[int(d1)][1]
        assert s == pytest.approx(want[int(d1)][0], rel=1e-5)

    # a scalar star query takes the same stacked path
    sql2 = "SELECT SUM(m), COUNT(*) FROM hc WHERE d1 < 50"
    assert isinstance(mesh_exec._plan_star_device(
        compile_query(sql2, schema), segs), StarSetPlan)
    r2 = mesh_exec.execute(segs, sql2)
    mm = cols["d1"] < 50
    assert r2.rows[0][1] == int(mm.sum())
    assert r2.rows[0][0] == pytest.approx(float(cols["m"][mm].sum()), rel=1e-5)
