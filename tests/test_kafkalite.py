"""KafkaLite socket-broker tests: the stream SPI proven over a REAL TCP boundary.

Reference scenario: realtime ingestion tests against embedded Kafka
(`KafkaDataServerStartable`, RealtimeClusterIntegrationTest) — here the broker is the
in-repo socket log broker and the consumption FSM runs against the `kafkalite`
stream plugin unchanged.
"""

import json
import threading

import numpy as np
import pytest

from pinot_tpu.cluster.enclosure import QuickCluster
from pinot_tpu.ingest.kafkalite import (KafkaLiteConsumer, LogBrokerClient,
                                        LogBrokerServer)
from pinot_tpu.schema import DataType, Schema, date_time, dimension, metric
from pinot_tpu.table import StreamConfig, TableConfig, TableType


@pytest.fixture()
def broker():
    srv = LogBrokerServer()
    yield srv
    srv.stop()


def test_produce_fetch_roundtrip(broker):
    client = LogBrokerClient(broker.bootstrap)
    client.create_topic("t", 2)
    offsets = [client.produce("t", f"m{i}", partition=i % 2) for i in range(6)]
    assert offsets == [0, 0, 1, 1, 2, 2]
    consumer = KafkaLiteConsumer(broker.bootstrap, "t", 0)
    batch = consumer.fetch(0, 100)
    assert [m.value for m in batch.messages] == ["m0", "m2", "m4"]
    assert batch.next_offset == 3
    assert consumer.latest_offset() == 3
    # resume from a mid-stream offset (opaque-offset contract)
    batch2 = consumer.fetch(batch.messages[1].offset, 100)
    assert [m.value for m in batch2.messages] == ["m2", "m4"]
    consumer.close()
    client.close()


def test_key_partitioning_and_metadata(broker):
    client = LogBrokerClient(broker.bootstrap)
    client.create_topic("keyed", 4)
    from pinot_tpu.ingest.stream import get_stream_factory
    factory = get_stream_factory("kafkalite", "keyed",
                                 {"bootstrap": broker.bootstrap})
    assert factory.metadata_provider().partition_count("keyed") == 4
    # same key -> same partition (client-side hashing, like a stock producer)
    p1 = client.partition_for("keyed", "k1")
    assert client.partition_for("keyed", "k1") == p1
    client.produce("keyed", "a", key="k1")
    client.produce("keyed", "b", key="k1")
    assert client.list_offsets("keyed", p1) == 2
    client.close()


def test_fetch_long_poll_wakes_on_produce(broker):
    client = LogBrokerClient(broker.bootstrap)
    client.create_topic("lp", 1)
    consumer = KafkaLiteConsumer(broker.bootstrap, "lp", 0)

    def produce_later():
        import time
        time.sleep(0.1)
        client.produce("lp", "late", partition=0)

    th = threading.Thread(target=produce_later)
    th.start()
    batch = consumer.fetch(0, 10, timeout_ms=5000)  # blocks until the produce
    th.join()
    assert [m.value for m in batch.messages] == ["late"]
    consumer.close()
    client.close()


def test_broker_restart_recovers_log(tmp_path):
    srv = LogBrokerServer(log_dir=str(tmp_path / "logs"))
    client = LogBrokerClient(srv.bootstrap)
    client.create_topic("durable", 1)
    for i in range(5):
        client.produce("durable", f"r{i}", partition=0)
    client.close()
    srv.stop()
    # restart on the same log dir: offsets and records must survive
    srv2 = LogBrokerServer(log_dir=str(tmp_path / "logs"))
    consumer = KafkaLiteConsumer(srv2.bootstrap, "durable", 0)
    assert [m.value for m in consumer.fetch(0, 100).messages] == \
        [f"r{i}" for i in range(5)]
    consumer.close()
    srv2.stop()


def test_realtime_table_consumes_from_socket_broker(tmp_path, broker):
    """The full FSM (CONSUMING -> commit -> ONLINE) against the socket broker,
    with the stream type switched by CONFIG ONLY — no FSM changes."""
    schema = Schema("clickstream", [
        dimension("user", DataType.STRING),
        metric("value", DataType.LONG),
        date_time("ts", DataType.LONG),
    ])
    client = LogBrokerClient(broker.bootstrap)
    client.create_topic("clicks", 2)

    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    cfg = TableConfig("clickstream", table_type=TableType.REALTIME, time_column="ts",
                      stream=StreamConfig(stream_type="kafkalite", topic="clicks",
                                          properties={"bootstrap": broker.bootstrap},
                                          flush_threshold_rows=10))
    cluster.controller.add_schema(schema)
    cluster.controller.add_realtime_table(cfg, num_partitions=2)

    for i in range(25):
        client.produce("clicks", json.dumps(
            {"user": f"u{i % 5}", "value": i, "ts": 1700000000000 + i}),
            partition=i % 2)

    total = 0
    for _ in range(6):
        total = cluster.query("SELECT COUNT(*) FROM clickstream LIMIT 5").rows[0][0]
        if total == 25:
            break
        cluster.pump_realtime(cfg.table_name_with_type)
    assert cluster.query("SELECT COUNT(*) FROM clickstream LIMIT 5").rows[0][0] == 25
    res = cluster.query(
        "SELECT user, SUM(value) FROM clickstream GROUP BY user ORDER BY user LIMIT 10")
    want = {}
    for i in range(25):
        want[f"u{i % 5}"] = want.get(f"u{i % 5}", 0) + i
    assert {r[0]: r[1] for r in res.rows} == want
    # committed (flushed) segments exist -> the FSM completed over the socket stream
    from pinot_tpu.cluster.catalog import STATUS_DONE
    metas = cluster.catalog.segments[cfg.table_name_with_type]
    assert any(m.status == STATUS_DONE for m in metas.values())
    client.close()
