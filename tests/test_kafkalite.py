"""KafkaLite socket-broker tests: the stream SPI proven over a REAL TCP boundary.

Reference scenario: realtime ingestion tests against embedded Kafka
(`KafkaDataServerStartable`, RealtimeClusterIntegrationTest) — here the broker is the
in-repo socket log broker and the consumption FSM runs against the `kafkalite`
stream plugin unchanged.
"""

import json
import threading

import numpy as np
import pytest

from pinot_tpu.cluster.enclosure import QuickCluster
from pinot_tpu.ingest.kafkalite import (KafkaLiteConsumer, LogBrokerClient,
                                        LogBrokerServer)
from pinot_tpu.schema import DataType, Schema, date_time, dimension, metric
from pinot_tpu.table import StreamConfig, TableConfig, TableType


@pytest.fixture()
def broker():
    srv = LogBrokerServer()
    yield srv
    srv.stop()


def test_produce_fetch_roundtrip(broker):
    client = LogBrokerClient(broker.bootstrap)
    client.create_topic("t", 2)
    offsets = [client.produce("t", f"m{i}", partition=i % 2) for i in range(6)]
    assert offsets == [0, 0, 1, 1, 2, 2]
    consumer = KafkaLiteConsumer(broker.bootstrap, "t", 0)
    batch = consumer.fetch(0, 100)
    assert [m.value for m in batch.messages] == ["m0", "m2", "m4"]
    assert batch.next_offset == 3
    assert consumer.latest_offset() == 3
    # resume from a mid-stream offset (opaque-offset contract)
    batch2 = consumer.fetch(batch.messages[1].offset, 100)
    assert [m.value for m in batch2.messages] == ["m2", "m4"]
    consumer.close()
    client.close()


def test_key_partitioning_and_metadata(broker):
    client = LogBrokerClient(broker.bootstrap)
    client.create_topic("keyed", 4)
    from pinot_tpu.ingest.stream import get_stream_factory
    factory = get_stream_factory("kafkalite", "keyed",
                                 {"bootstrap": broker.bootstrap})
    assert factory.metadata_provider().partition_count("keyed") == 4
    # same key -> same partition (client-side hashing, like a stock producer)
    p1 = client.partition_for("keyed", "k1")
    assert client.partition_for("keyed", "k1") == p1
    client.produce("keyed", "a", key="k1")
    client.produce("keyed", "b", key="k1")
    assert client.list_offsets("keyed", p1) == 2
    client.close()


def test_fetch_long_poll_wakes_on_produce(broker):
    client = LogBrokerClient(broker.bootstrap)
    client.create_topic("lp", 1)
    consumer = KafkaLiteConsumer(broker.bootstrap, "lp", 0)

    def produce_later():
        import time
        time.sleep(0.1)
        client.produce("lp", "late", partition=0)

    th = threading.Thread(target=produce_later)
    th.start()
    batch = consumer.fetch(0, 10, timeout_ms=5000)  # blocks until the produce
    th.join()
    assert [m.value for m in batch.messages] == ["late"]
    consumer.close()
    client.close()


def test_broker_restart_recovers_log(tmp_path):
    srv = LogBrokerServer(log_dir=str(tmp_path / "logs"))
    client = LogBrokerClient(srv.bootstrap)
    client.create_topic("durable", 1)
    for i in range(5):
        client.produce("durable", f"r{i}", partition=0)
    client.close()
    srv.stop()
    # restart on the same log dir: offsets and records must survive
    srv2 = LogBrokerServer(log_dir=str(tmp_path / "logs"))
    consumer = KafkaLiteConsumer(srv2.bootstrap, "durable", 0)
    assert [m.value for m in consumer.fetch(0, 100).messages] == \
        [f"r{i}" for i in range(5)]
    consumer.close()
    srv2.stop()


def test_realtime_table_consumes_from_socket_broker(tmp_path, broker):
    """The full FSM (CONSUMING -> commit -> ONLINE) against the socket broker,
    with the stream type switched by CONFIG ONLY — no FSM changes."""
    schema = Schema("clickstream", [
        dimension("user", DataType.STRING),
        metric("value", DataType.LONG),
        date_time("ts", DataType.LONG),
    ])
    client = LogBrokerClient(broker.bootstrap)
    client.create_topic("clicks", 2)

    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    cfg = TableConfig("clickstream", table_type=TableType.REALTIME, time_column="ts",
                      stream=StreamConfig(stream_type="kafkalite", topic="clicks",
                                          properties={"bootstrap": broker.bootstrap},
                                          flush_threshold_rows=10))
    cluster.controller.add_schema(schema)
    cluster.controller.add_realtime_table(cfg, num_partitions=2)

    for i in range(25):
        client.produce("clicks", json.dumps(
            {"user": f"u{i % 5}", "value": i, "ts": 1700000000000 + i}),
            partition=i % 2)

    total = 0
    for _ in range(6):
        total = cluster.query("SELECT COUNT(*) FROM clickstream LIMIT 5").rows[0][0]
        if total == 25:
            break
        cluster.pump_realtime(cfg.table_name_with_type)
    assert cluster.query("SELECT COUNT(*) FROM clickstream LIMIT 5").rows[0][0] == 25
    res = cluster.query(
        "SELECT user, SUM(value) FROM clickstream GROUP BY user ORDER BY user LIMIT 10")
    want = {}
    for i in range(25):
        want[f"u{i % 5}"] = want.get(f"u{i % 5}", 0) + i
    assert {r[0]: r[1] for r in res.rows} == want
    # committed (flushed) segments exist -> the FSM completed over the socket stream
    from pinot_tpu.cluster.catalog import STATUS_DONE
    metas = cluster.catalog.segments[cfg.table_name_with_type]
    assert any(m.status == STATUS_DONE for m in metas.values())
    client.close()


# -- r4: CRC'd v2 batches ARE the durable artifact (verdict weak #6) ---------

def test_log_stores_raw_crc_batches_with_binary_fidelity(tmp_path):
    """The on-disk partition log is a sequence of offset-patched v2 record
    batches whose CRCs are the PRODUCER's — restart replays byte-identical
    batches, never a reconstruction."""
    import struct

    from pinot_tpu.ingest import kafka_wire as kw

    srv = LogBrokerServer(log_dir=str(tmp_path / "logs"))
    client = LogBrokerClient(srv.bootstrap)
    client.create_topic("t", 1)
    client.produce_many("t", [f"m{i}" for i in range(5)])
    client.produce("t", "single", timestamp_ms=123)
    before = client.fetch("t", 0, 0)
    srv.stop()

    # the stored artifact: parse the raw .log frames, verify each CRC
    log_path = tmp_path / "logs" / "t" / "0.log"
    data = log_path.read_bytes()
    frames = []
    pos = 0
    while pos + 12 <= len(data):
        (blen,) = struct.unpack(">i", data[pos + 8:pos + 12])
        frames.append(data[pos:pos + 12 + blen])
        pos += 12 + blen
    assert len(frames) == 2                      # one per produce call
    for f in frames:
        (crc,) = struct.unpack(">I", f[17:21])
        assert kw.crc32c(f[21:]) == crc          # producer CRC preserved
    (base0,) = struct.unpack(">q", frames[0][:8])
    (base1,) = struct.unpack(">q", frames[1][:8])
    assert (base0, base1) == (0, 5)              # offsets patched in

    # restart: served bytes decode to the identical records
    srv2 = LogBrokerServer(log_dir=str(tmp_path / "logs"))
    try:
        client2 = LogBrokerClient(srv2.bootstrap)
        after = client2.fetch("t", 0, 0)
        assert after == before
        assert [v for _o, _t, _k, v in after] == \
            [f"m{i}".encode() for i in range(5)] + [b"single"]
        assert after[-1][1] == 123               # explicit timestamp survives
    finally:
        srv2.stop()


def test_torn_tail_truncated_on_recovery(tmp_path):
    """A crash mid-append leaves a partial frame; recovery truncates to the
    last complete batch and serves the intact prefix (reference: log segment
    recovery)."""
    srv = LogBrokerServer(log_dir=str(tmp_path / "logs"))
    client = LogBrokerClient(srv.bootstrap)
    client.create_topic("t", 1)
    client.produce_many("t", ["a", "b", "c"])
    srv.stop()
    log_path = tmp_path / "logs" / "t" / "0.log"
    intact = log_path.read_bytes()
    log_path.write_bytes(intact + intact[:20])   # torn half-frame tail
    srv2 = LogBrokerServer(log_dir=str(tmp_path / "logs"))
    try:
        client2 = LogBrokerClient(srv2.bootstrap)
        recs = client2.fetch("t", 0, 0)
        assert [v for _o, _t, _k, v in recs] == [b"a", b"b", b"c"]
        assert client2.list_offsets("t", 0) == 3
        # the file was healed in place
        assert log_path.read_bytes()[:len(intact)] == intact
        assert len(log_path.read_bytes()) == len(intact)
    finally:
        srv2.stop()


def test_legacy_jsonl_log_converted(tmp_path):
    """Partition logs from older builds (JSONL) convert once at load and keep
    their records and offsets."""
    import json as _json
    tdir = tmp_path / "logs" / "t"
    tdir.mkdir(parents=True)
    with open(tdir / "0.jsonl", "w") as f:
        for i in range(4):
            f.write(_json.dumps({"v": f"old{i}", "k": None, "t": 1000 + i})
                    + "\n")
    srv = LogBrokerServer(log_dir=str(tmp_path / "logs"))
    try:
        client = LogBrokerClient(srv.bootstrap)
        recs = client.fetch("t", 0, 0)
        assert [v for _o, _t, _k, v in recs] == \
            [f"old{i}".encode() for i in range(4)]
        assert [t for _o, t, _k, _v in recs] == [1000, 1001, 1002, 1003]
        # appends continue in the binary log
        client.produce("t", "new")
        assert client.list_offsets("t", 0) == 5
    finally:
        srv.stop()


def test_client_reconnects_after_broker_restart(tmp_path):
    """A stream-broker restart must not permanently stall consumers: the
    client transparently reconnects its dead socket on the next request
    (stock-Kafka-client behavior); offsets continue from the durable log."""
    import time as _t
    srv = LogBrokerServer(log_dir=str(tmp_path / "logs"))
    client = LogBrokerClient(srv.bootstrap)
    client.create_topic("t", 1)
    client.produce_many("t", ["a", "b"])
    assert len(client.fetch("t", 0, 0)) == 2
    port = int(srv.bootstrap.split(":")[1])
    srv.stop()
    srv2 = None
    for _ in range(100):
        try:
            srv2 = LogBrokerServer(log_dir=str(tmp_path / "logs"), port=port)
            break
        except OSError:
            _t.sleep(0.1)
    assert srv2 is not None
    try:
        # SAME client object, dead socket: the next fetch reconnects
        recs = client.fetch("t", 0, 0)
        assert [v for _o, _ts, _k, v in recs] == [b"a", b"b"]
        client.produce("t", "c")
        assert client.list_offsets("t", 0) == 3
    finally:
        srv2.stop()


def test_boolean_truthiness_on_batch_fast_path(tmp_path):
    """Review round: BOOLEAN columns coerce by truthiness on the batched
    consume path too (2 -> 1, 0.5 -> 1), identically with and without a None
    in the batch."""
    from pinot_tpu.ingest.transform import TransformPipeline
    from pinot_tpu.schema import DataType, Schema, dimension, metric

    schema = Schema("b", [dimension("k"), metric("flag", DataType.BOOLEAN)])
    p = TransformPipeline(schema)
    clean = p.apply({"k": ["a", "b", "c"], "flag": [0, 2, 0.5]})
    dirty = p.apply({"k": ["a", "b", "c", "d"], "flag": [0, 2, 0.5, None]})
    assert clean["flag"] == [0, 1, 1]
    assert dirty["flag"] == [0, 1, 1, None]


def test_legacy_conversion_crash_safe(tmp_path):
    """Review round: a torn temp file from a crashed legacy conversion never
    shadows the intact .jsonl — the retry converts it fully."""
    import json as _json
    tdir = tmp_path / "logs" / "t"
    tdir.mkdir(parents=True)
    with open(tdir / "0.jsonl", "w") as f:
        for i in range(3):
            f.write(_json.dumps({"v": f"x{i}", "k": None, "t": i}) + "\n")
    # simulate a crashed conversion: a stale tmp file lies around
    (tdir / "0.log.tmp.999").write_bytes(b"\x00" * 10)
    srv = LogBrokerServer(log_dir=str(tmp_path / "logs"))
    try:
        client = LogBrokerClient(srv.bootstrap)
        recs = client.fetch("t", 0, 0)
        assert [v for _o, _t, _k, v in recs] == [b"x0", b"x1", b"x2"]
    finally:
        srv.stop()
