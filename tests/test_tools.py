"""CLI admin tool, segment tools, and controller admin REST tests.

Reference pattern: pinot-admin command tests (AddTable/UploadSegment/PostQuery),
SegmentDumpTool, ValidateSegment.
"""

import json

import numpy as np
import pytest

from pinot_tpu.schema import DataType, FieldSpec, FieldRole, Schema, dimension, metric
from pinot_tpu.segment.writer import SegmentBuilder, SegmentGeneratorConfig
from pinot_tpu.tools.admin import main as admin_main
from pinot_tpu.tools.segment import dump_segment, verify_segment

SCHEMA = Schema("trips", [
    dimension("city", DataType.STRING),
    FieldSpec("tags", DataType.STRING, FieldRole.DIMENSION, single_value=False),
    metric("fare", DataType.DOUBLE),
])


@pytest.fixture(scope="module")
def seg_dir(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("tools")
    return SegmentBuilder(SCHEMA, SegmentGeneratorConfig(
        inverted_index_columns=["city"])).build(
        {"city": ["nyc", "sf", "nyc"], "tags": [["a"], ["a", "b"], None],
         "fare": np.array([10.0, 20.0, 30.0])}, str(tmp), "trips_0")


# -- segment tools -------------------------------------------------------------

def test_dump_segment(seg_dir):
    d = dump_segment(seg_dir, max_rows=2)
    assert d["segmentName"] == "trips_0"
    assert d["totalDocs"] == 3
    assert d["columns"]["city"]["indexes"] == ["inverted"]
    assert d["columns"]["tags"]["multiValue"] is True
    assert d["columns"]["fare"]["minValue"] == 10.0
    assert len(d["sampleRows"]) == 2
    assert d["sampleRows"][0][0] == "nyc"
    json.dumps(d)  # fully JSON-serializable


def test_verify_segment_clean(seg_dir):
    report = verify_segment(seg_dir)
    assert report["ok"], report
    names = [c["name"] for c in report["checks"]]
    assert "crc" in names and "column:tags" in names


def test_verify_segment_detects_corruption(tmp_path):
    seg = SegmentBuilder(SCHEMA).build(
        {"city": ["a"], "tags": [["t"]], "fare": np.array([1.0])},
        str(tmp_path), "bad_0")
    # flip bytes in a column file -> crc must fail
    import glob
    import os
    victim = sorted(glob.glob(os.path.join(seg, "cols", "fare*")))[0]
    data = bytearray(open(victim, "rb").read())
    data[-1] ^= 0xFF
    open(victim, "wb").write(bytes(data))
    report = verify_segment(seg)
    assert not report["ok"]
    assert any(c["name"] == "crc" and not c["ok"] for c in report["checks"])


# -- CLI ----------------------------------------------------------------------

def test_cli_dump_and_verify(seg_dir, capsys):
    assert admin_main(["dump-segment", "--dir", seg_dir]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["segmentName"] == "trips_0"
    assert admin_main(["verify-segment", "--dir", seg_dir]) == 0


def test_cli_build_segment(tmp_path, capsys):
    schema_file = tmp_path / "schema.json"
    schema_file.write_text(json.dumps(SCHEMA.to_json()))
    rows_file = tmp_path / "rows.jsonl"
    rows_file.write_text('{"city": "la", "tags": ["x"], "fare": 5.5}\n'
                         '{"city": "sd", "tags": ["y"], "fare": 6.5}\n')
    rc = admin_main(["build-segment", "--schema", str(schema_file),
                     "--input", str(rows_file), "--out", str(tmp_path / "segs"),
                     "--name", "built_0"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["rows"] == 2
    from pinot_tpu.segment.reader import load_segment
    seg = load_segment(out["segmentDir"])
    assert seg.num_docs == 2


def test_cli_against_http_cluster(tmp_path, capsys):
    """Schema/table/segment/query round-trip through the CLI against real HTTP
    services (the pinot-admin quickstart path)."""
    from pinot_tpu.cluster.broker import Broker
    from pinot_tpu.cluster.catalog import Catalog
    from pinot_tpu.cluster.controller import Controller
    from pinot_tpu.cluster.deepstore import LocalDeepStore
    from pinot_tpu.cluster.remote import (ControllerDeepStore, RemoteCatalog,
                                          RemoteServerHandle)
    from pinot_tpu.cluster.server import ServerNode
    from pinot_tpu.cluster.services import (BrokerService, ControllerService,
                                            ServerService)
    from pinot_tpu.table import TableConfig

    catalog = Catalog()
    ctrl = Controller("c0", catalog, LocalDeepStore(str(tmp_path / "ds")),
                      str(tmp_path / "c"))
    csvc = ControllerService(ctrl)
    rc_cat = RemoteCatalog(csvc.url, poll_timeout_s=1.0)
    node = ServerNode("server_0", rc_cat, ControllerDeepStore(csvc.url),
                      str(tmp_path / "s0"))
    ssvc = ServerService(node)
    brc = RemoteCatalog(csvc.url, poll_timeout_s=1.0)
    broker = Broker("b0", brc)
    bsvc = BrokerService(broker)
    try:
        schema_file = tmp_path / "schema.json"
        schema_file.write_text(json.dumps(SCHEMA.to_json()))
        table_file = tmp_path / "table.json"
        table_file.write_text(json.dumps(TableConfig("trips").to_json()))
        assert admin_main(["add-schema", "--controller", csvc.url,
                           "--file", str(schema_file)]) == 0
        assert admin_main(["add-table", "--controller", csvc.url,
                           "--file", str(table_file)]) == 0
        capsys.readouterr()
        assert admin_main(["list-tables", "--controller", csvc.url]) == 0
        assert "trips_OFFLINE" in json.loads(capsys.readouterr().out)["tables"]

        seg = SegmentBuilder(SCHEMA).build(
            {"city": ["nyc", "sf"], "tags": [["a"], ["b"]],
             "fare": np.array([1.0, 2.0])}, str(tmp_path / "b"), "trips_0")
        assert admin_main(["upload-segment", "--controller", csvc.url,
                           "--table", "trips_OFFLINE", "--dir", seg]) == 0
        # retry until the broker's catalog mirror + routing converge (segment
        # load and broker snapshot polls race the first query)
        import time
        deadline = time.time() + 20
        rows = None
        while time.time() < deadline:
            capsys.readouterr()
            try:
                rc = admin_main(["query", "--broker", bsvc.url, "--json",
                                 "--sql", "SELECT SUM(fare) FROM trips"])
            except Exception:  # broker mirror not converged: 500 -> retry
                time.sleep(0.2)
                continue
            if rc == 0:
                rows = json.loads(capsys.readouterr().out)["resultTable"]["rows"]
                if rows and rows[0][0] == 3.0:
                    break
            time.sleep(0.2)
        assert rows and rows[0][0] == 3.0, f"no converged result: {rows}"

        assert admin_main(["table-status", "--controller", csvc.url,
                           "--table", "trips_OFFLINE"]) == 0
        status = json.loads(capsys.readouterr().out)
        assert status["converged"] is True

        # admin read APIs
        from pinot_tpu.cluster.http_service import get_json
        metas = get_json(f"{csvc.url}/segmentsMeta/trips_OFFLINE")["segments"]
        assert "trips_0" in metas
        cfg = get_json(f"{csvc.url}/tables/trips_OFFLINE")["config"]
        assert cfg["tableName"] == "trips" or "trips" in json.dumps(cfg)
        schema_json = get_json(f"{csvc.url}/schemas/trips")
        assert schema_json["schemaName"] == "trips"
    finally:
        rc_cat.close()
        brc.close()
        for s in (csvc, ssvc, bsvc):
            s.stop()
