"""Tuner/recommender + dataframe connector tests.

Reference patterns: controller recommender rules engine, spark connector's
dataframe -> segment write path.
"""

import numpy as np
import pytest

from pinot_tpu.schema import DataType, FieldRole, Schema, dimension, metric
from pinot_tpu.segment.reader import load_segment
from pinot_tpu.segment.writer import SegmentBuilder, SegmentGeneratorConfig
from pinot_tpu.tools.tuner import analyze_segment, recommend


@pytest.fixture(scope="module")
def seg_dir(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("tuner")
    rng = np.random.default_rng(6)
    n = 5000
    return SegmentBuilder(Schema("ev", [
        dimension("country"),                       # low cardinality
        dimension("user_id"),                       # high cardinality string
        metric("price", DataType.DOUBLE),           # high cardinality numeric
        metric("qty", DataType.INT),                # low cardinality numeric
    ])).build({
        "country": [f"c{i % 20}" for i in range(n)],
        "user_id": [f"u{i}" for i in range(n)],
        "price": np.round(rng.uniform(0, 1e6, n), 4),
        "qty": (np.arange(n) % 9).astype(np.int32),
    }, str(tmp), "ev_0")


def test_analyze_profile(seg_dir):
    p = analyze_segment(seg_dir)
    assert p["country"]["cardinality"] == 20
    assert p["country"]["cardinalityRatio"] < 0.01
    assert p["price"]["hasDictionary"] is False   # writer's raw heuristic
    assert p["user_id"]["cardinalityRatio"] == 1.0


def test_recommendations(seg_dir):
    rec = recommend(seg_dir, filter_columns=["country", "price"],
                    group_by_columns=["country"], agg_columns=["price", "qty"])
    idx = rec["indexing"]
    assert "country" in idx["invertedIndexColumns"]     # low-card filtered dim
    # raw columns cannot carry a range index (dict ids only): min/max pruning +
    # device compares serve ranges; bloom covers EQ
    assert "price" not in idx["rangeIndexColumns"]
    assert "price" in idx["noDictionaryColumns"]
    assert "price" in idx["bloomFilterColumns"]
    assert "user_id" not in idx["invertedIndexColumns"]  # unfiltered high-card
    st = idx["starTreeIndexConfigs"]
    assert st and st[0]["dimensionsSplitOrder"] == ["country"]
    assert any("SUM__price" in p for p in st[0]["functionColumnPairs"])
    assert rec["rationale"]                              # every choice explained
    # the recommendation round-trips into a working build config
    from pinot_tpu.table import IndexingConfig
    cfg = IndexingConfig.from_json(idx)
    assert SegmentGeneratorConfig.from_indexing(cfg).inverted_index_columns \
        == ["country"]


# -- dataframe connector ------------------------------------------------------

def test_dataframe_roundtrip(tmp_path):
    import pandas as pd
    from pinot_tpu.ingest.dataframe import (schema_from_dataframe,
                                            segments_from_dataframe)
    from pinot_tpu.query.executor import execute_query
    df = pd.DataFrame({
        "city": ["nyc", "sf", "nyc", None],
        "fare": [10.0, 20.0, 30.0, 5.0],
        "n": np.array([1, 2, 3, 4], dtype=np.int64),
    })
    schema = schema_from_dataframe(df, "trips", metrics=["fare", "n"])
    assert schema.field_spec("fare").role is FieldRole.METRIC
    assert schema.field_spec("city").data_type is DataType.STRING
    dirs = segments_from_dataframe(df, schema, str(tmp_path), "trips")
    assert len(dirs) == 1
    seg = load_segment(dirs[0])
    assert seg.num_docs == 4
    res = execute_query([seg], "SELECT SUM(fare) FROM trips WHERE city = 'nyc'")
    assert res.rows[0][0] == pytest.approx(40.0)
    # the None city row landed as a recorded null
    res = execute_query([seg], "SELECT COUNT(*) FROM trips WHERE city IS NULL")
    assert res.rows[0][0] == 1


def test_dataframe_partitions_and_push(tmp_path):
    import pandas as pd
    from pinot_tpu.cluster import QuickCluster
    from pinot_tpu.ingest.dataframe import push_dataframe, schema_from_dataframe
    from pinot_tpu.table import TableConfig
    parts = [pd.DataFrame({"k": [f"p{i}"] * 100, "v": np.arange(100.0)})
             for i in range(3)]
    schema = schema_from_dataframe(parts[0], "pt", metrics=["v"])
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    cluster.create_table(schema, TableConfig("pt"))
    dirs = push_dataframe(iter(parts), schema, cluster.controller, "pt_OFFLINE",
                          str(tmp_path / "b"))
    assert len(dirs) == 3               # one segment per partition frame
    res = cluster.query("SELECT k, COUNT(*) FROM pt GROUP BY k LIMIT 10")
    assert sorted((r[0], r[1]) for r in res.rows) == \
        [("p0", 100), ("p1", 100), ("p2", 100)]


# -- r4: workload-driven advisors (reference: recommender rules engine) ------

def _workload_segment(tmp_path_factory):
    import numpy as np
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.segment.writer import SegmentBuilder
    tmp = tmp_path_factory.mktemp("tuner_wl")
    rng = np.random.default_rng(23)
    n = 20_000
    schema = Schema("orders", [
        dimension("customer_id", DataType.STRING),   # high-card, EQ-filtered
        dimension("region", DataType.STRING),        # low-card group-by
        dimension("payload", DataType.JSON),
        metric("price", DataType.DOUBLE),
        metric("seq", DataType.LONG),                # unique per row
    ])
    cols = {
        "customer_id": [f"c{int(x)}" for x in rng.integers(0, 5000, n)],
        "region": rng.choice(["NA", "EU", "APAC"], n).tolist(),
        "payload": ['{"k": %d}' % int(i % 7) for i in range(n)],
        "price": np.round(rng.uniform(1, 500, n), 2),
        "seq": np.arange(n),
    }
    return SegmentBuilder(schema).build(cols, str(tmp), "orders_0")


WORKLOAD = [
    "SELECT COUNT(*) FROM orders WHERE customer_id = 'c42'",
    "SELECT SUM(price) FROM orders WHERE customer_id IN ('c1', 'c2')",
    "SELECT region, SUM(price) FROM orders GROUP BY region",
    "SELECT COUNT(*) FROM orders WHERE customer_id = 'c7' AND price > 100",
    "SELECT COUNT(*) FROM orders WHERE JSON_MATCH(payload, '\"$.k\" = 3')",
]


def test_analyze_workload_counts(tmp_path_factory):
    from pinot_tpu.tools.tuner import analyze_workload
    usage = analyze_workload(WORKLOAD)
    assert usage["customer_id"]["eq"] == 3
    assert usage["price"]["range"] == 1 and usage["price"]["agg"] == 2
    assert usage["region"]["group"] == 1
    assert usage["payload"]["json"] == 1


def test_partition_advisor_picks_eq_filtered_high_card(tmp_path_factory):
    from pinot_tpu.tools.tuner import recommend_partitioning
    seg = _workload_segment(tmp_path_factory)
    adv = recommend_partitioning(seg, WORKLOAD, num_servers=4)
    assert adv["partitionColumn"] == "customer_id"
    assert adv["numPartitions"] == 16        # pow2 >= 4 servers x 4
    assert any("prune" in r for r in adv["rationale"])
    # a workload with no EQ filters gets NO partition column
    adv2 = recommend_partitioning(
        seg, ["SELECT region, SUM(price) FROM orders GROUP BY region"],
        num_servers=4)
    assert adv2["partitionColumn"] is None


def test_realtime_provisioning_advisor():
    from pinot_tpu.tools.tuner import recommend_realtime_provisioning
    small = recommend_realtime_provisioning(
        events_per_sec=5_000, avg_row_bytes=100, retention_hours=24,
        host_memory_gb=32, num_hosts=2)
    assert small["numPartitions"] >= 1 and small["fitsInMemory"]
    assert small["flushThresholdRows"] >= 10_000
    big = recommend_realtime_provisioning(
        events_per_sec=500_000, avg_row_bytes=500, retention_hours=168,
        host_memory_gb=16, num_hosts=2)
    assert big["numPartitions"] > small["numPartitions"]
    assert not big["fitsInMemory"] and big["recommendedNumHosts"] > 2
    assert big["retainedDiskMbPerHost"] > big["estimatedPerHostMb"]


def test_recommend_from_workload_full_report(tmp_path_factory):
    from pinot_tpu.tools.tuner import recommend_from_workload
    seg = _workload_segment(tmp_path_factory)
    rec = recommend_from_workload(seg, WORKLOAD, num_servers=4)
    idx = rec["indexing"]
    assert "payload" in idx["jsonIndexColumns"]          # JSON_MATCH rule
    assert idx["sortedColumn"] == "customer_id"          # most-EQ rule
    assert "seq" in idx["noDictionaryColumns"]           # unique-per-row metric
    assert rec["partitioning"]["partitionColumn"] == "customer_id"
    assert rec["rationale"]


def test_partition_advisor_scores_per_query_not_per_predicate(
        tmp_path_factory):
    """Review round: the score is the fraction of QUERIES that prune on the
    column — one query with many unrelated EQ predicates must not dilute a
    column that appears in every query."""
    from pinot_tpu.tools.tuner import recommend_partitioning
    seg = _workload_segment(tmp_path_factory)
    noisy = [
        "SELECT COUNT(*) FROM orders WHERE customer_id = 'c1' AND "
        "region = 'NA' AND seq = 1 AND seq = 2 AND seq = 3 AND seq = 4",
        "SELECT COUNT(*) FROM orders WHERE customer_id = 'c2'",
        "SELECT COUNT(*) FROM orders WHERE customer_id = 'c3'",
    ]
    adv = recommend_partitioning(seg, noisy, num_servers=4)
    assert adv["partitionColumn"] == "customer_id", adv
