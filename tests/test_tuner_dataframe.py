"""Tuner/recommender + dataframe connector tests.

Reference patterns: controller recommender rules engine, spark connector's
dataframe -> segment write path.
"""

import numpy as np
import pytest

from pinot_tpu.schema import DataType, FieldRole, Schema, dimension, metric
from pinot_tpu.segment.reader import load_segment
from pinot_tpu.segment.writer import SegmentBuilder, SegmentGeneratorConfig
from pinot_tpu.tools.tuner import analyze_segment, recommend


@pytest.fixture(scope="module")
def seg_dir(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("tuner")
    rng = np.random.default_rng(6)
    n = 5000
    return SegmentBuilder(Schema("ev", [
        dimension("country"),                       # low cardinality
        dimension("user_id"),                       # high cardinality string
        metric("price", DataType.DOUBLE),           # high cardinality numeric
        metric("qty", DataType.INT),                # low cardinality numeric
    ])).build({
        "country": [f"c{i % 20}" for i in range(n)],
        "user_id": [f"u{i}" for i in range(n)],
        "price": np.round(rng.uniform(0, 1e6, n), 4),
        "qty": (np.arange(n) % 9).astype(np.int32),
    }, str(tmp), "ev_0")


def test_analyze_profile(seg_dir):
    p = analyze_segment(seg_dir)
    assert p["country"]["cardinality"] == 20
    assert p["country"]["cardinalityRatio"] < 0.01
    assert p["price"]["hasDictionary"] is False   # writer's raw heuristic
    assert p["user_id"]["cardinalityRatio"] == 1.0


def test_recommendations(seg_dir):
    rec = recommend(seg_dir, filter_columns=["country", "price"],
                    group_by_columns=["country"], agg_columns=["price", "qty"])
    idx = rec["indexing"]
    assert "country" in idx["invertedIndexColumns"]     # low-card filtered dim
    # raw columns cannot carry a range index (dict ids only): min/max pruning +
    # device compares serve ranges; bloom covers EQ
    assert "price" not in idx["rangeIndexColumns"]
    assert "price" in idx["noDictionaryColumns"]
    assert "price" in idx["bloomFilterColumns"]
    assert "user_id" not in idx["invertedIndexColumns"]  # unfiltered high-card
    st = idx["starTreeIndexConfigs"]
    assert st and st[0]["dimensionsSplitOrder"] == ["country"]
    assert any("SUM__price" in p for p in st[0]["functionColumnPairs"])
    assert rec["rationale"]                              # every choice explained
    # the recommendation round-trips into a working build config
    from pinot_tpu.table import IndexingConfig
    cfg = IndexingConfig.from_json(idx)
    assert SegmentGeneratorConfig.from_indexing(cfg).inverted_index_columns \
        == ["country"]


# -- dataframe connector ------------------------------------------------------

def test_dataframe_roundtrip(tmp_path):
    import pandas as pd
    from pinot_tpu.ingest.dataframe import (schema_from_dataframe,
                                            segments_from_dataframe)
    from pinot_tpu.query.executor import execute_query
    df = pd.DataFrame({
        "city": ["nyc", "sf", "nyc", None],
        "fare": [10.0, 20.0, 30.0, 5.0],
        "n": np.array([1, 2, 3, 4], dtype=np.int64),
    })
    schema = schema_from_dataframe(df, "trips", metrics=["fare", "n"])
    assert schema.field_spec("fare").role is FieldRole.METRIC
    assert schema.field_spec("city").data_type is DataType.STRING
    dirs = segments_from_dataframe(df, schema, str(tmp_path), "trips")
    assert len(dirs) == 1
    seg = load_segment(dirs[0])
    assert seg.num_docs == 4
    res = execute_query([seg], "SELECT SUM(fare) FROM trips WHERE city = 'nyc'")
    assert res.rows[0][0] == pytest.approx(40.0)
    # the None city row landed as a recorded null
    res = execute_query([seg], "SELECT COUNT(*) FROM trips WHERE city IS NULL")
    assert res.rows[0][0] == 1


def test_dataframe_partitions_and_push(tmp_path):
    import pandas as pd
    from pinot_tpu.cluster import QuickCluster
    from pinot_tpu.ingest.dataframe import push_dataframe, schema_from_dataframe
    from pinot_tpu.table import TableConfig
    parts = [pd.DataFrame({"k": [f"p{i}"] * 100, "v": np.arange(100.0)})
             for i in range(3)]
    schema = schema_from_dataframe(parts[0], "pt", metrics=["v"])
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    cluster.create_table(schema, TableConfig("pt"))
    dirs = push_dataframe(iter(parts), schema, cluster.controller, "pt_OFFLINE",
                          str(tmp_path / "b"))
    assert len(dirs) == 3               # one segment per partition frame
    res = cluster.query("SELECT k, COUNT(*) FROM pt GROUP BY k LIMIT 10")
    assert sorted((r[0], r[1]) for r in res.rows) == \
        [("p0", 100), ("p1", 100), ("p2", 100)]
