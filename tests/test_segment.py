"""Segment format round-trip tests (reference pattern: reader/creator unit tests that
round-trip files in temp dirs, SURVEY.md §4.1)."""

import numpy as np
import pytest

from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.segment import SegmentBuilder, SegmentGeneratorConfig, load_segment
from pinot_tpu.segment.dictionary import build_dictionary


def test_roundtrip_values(ssb_segment_dir, ssb_schema):
    seg_dir, cols = ssb_segment_dir
    seg = load_segment(seg_dir)
    assert seg.num_docs == 4096
    assert set(seg.column_names) == set(ssb_schema.column_names)
    for name, raw in cols.items():
        col = seg.column(name)
        got = col.values()
        if isinstance(raw, np.ndarray) and raw.dtype.kind == "f":
            np.testing.assert_allclose(got.astype(np.float64), raw, rtol=1e-6)
        elif isinstance(raw, np.ndarray):
            np.testing.assert_array_equal(got.astype(raw.dtype), raw)
        else:
            assert list(got) == list(raw)


def test_dictionary_resolution(ssb_segment_dir):
    seg_dir, cols = ssb_segment_dir
    seg = load_segment(seg_dir)
    d = seg.column("lo_region").dictionary
    assert d is not None
    assert sorted(set(cols["lo_region"])) == list(d.values)
    assert d.index_of("ASIA") >= 0
    assert d.index_of("NOWHERE") == -1
    lo, hi = d.id_range("AMERICA", "ASIA")
    assert [d.get(i) for i in range(lo, hi)] == ["AMERICA", "ASIA"]
    # LIKE over the dictionary
    ids = d.ids_matching_like("A%")
    assert {d.get(i) for i in ids} == {"AFRICA", "AMERICA", "ASIA"}


def test_dict_id_width_minimal(ssb_segment_dir):
    seg_dir, _ = ssb_segment_dir
    seg = load_segment(seg_dir)
    region = seg.column("lo_region")
    assert region.fwd.dtype == np.uint8  # 5 regions fit in one byte
    assert region.cardinality == 5


def test_inverted_index(ssb_segment_dir, ssb_schema):
    seg_dir, cols = ssb_segment_dir
    seg = load_segment(seg_dir)
    col = seg.column("lo_region")
    inv = col.inverted_index
    assert inv is not None
    d = col.dictionary
    asia_id = d.index_of("ASIA")
    docs = inv.doc_ids_for(asia_id)
    expect = np.nonzero(np.array(cols["lo_region"], dtype=object) == "ASIA")[0]
    np.testing.assert_array_equal(np.sort(docs), expect)
    assert inv.match_count_for_range(asia_id, asia_id + 1) == len(expect)


def test_range_index(ssb_segment_dir, ssb_schema):
    seg_dir, cols = ssb_segment_dir
    from pinot_tpu.segment.format import unpack_bitmap
    seg = load_segment(seg_dir)
    col = seg.column("lo_discount")
    rng_idx = col.range_index
    assert rng_idx is not None
    d = col.dictionary
    lo, hi = d.id_range(1, 3)  # discount between 1 and 3 inclusive
    mask = unpack_bitmap(rng_idx.mask_range(lo, hi), seg.num_docs)
    expect = (cols["lo_discount"] >= 1) & (cols["lo_discount"] <= 3)
    np.testing.assert_array_equal(mask, expect)


def test_bloom_filter(ssb_segment_dir):
    seg_dir, cols = ssb_segment_dir
    seg = load_segment(seg_dir)
    bf = seg.column("lo_brand").bloom_filter
    assert bf is not None
    for v in set(cols["lo_brand"]):
        assert bf.might_contain(v)
    misses = sum(bf.might_contain(f"NOPE#{i}") for i in range(200))
    assert misses <= 10  # ~1% fpp


def test_nulls_and_defaults(tmp_path):
    schema = Schema("t", [dimension("s", DataType.STRING), metric("m", DataType.DOUBLE)])
    cols = {"s": ["a", None, "b", None], "m": np.array([1.0, 2.0, 3.0, 4.0])}
    seg_dir = SegmentBuilder(schema).build(cols, str(tmp_path), "t_0")
    seg = load_segment(seg_dir)
    s = seg.column("s")
    np.testing.assert_array_equal(s.null_bitmap, [False, True, False, True])
    assert list(s.values()) == ["a", "null", "b", "null"]
    assert seg.column("m").null_bitmap is None


def test_raw_encoding_for_high_cardinality_metric(tmp_path):
    schema = Schema("t", [metric("m", DataType.DOUBLE)])
    vals = np.arange(1000, dtype=np.float64) + 0.5
    seg_dir = SegmentBuilder(schema).build({"m": vals}, str(tmp_path), "t_0")
    col = load_segment(seg_dir).column("m")
    assert not col.has_dictionary
    assert col.fwd.dtype == np.float64
    assert col.min_value == 0.5 and col.max_value == 999.5


def test_sorted_detection(tmp_path):
    schema = Schema("t", [dimension("k", DataType.INT)])
    seg_dir = SegmentBuilder(schema).build({"k": np.arange(100, dtype=np.int32)},
                                           str(tmp_path), "t_0")
    assert load_segment(seg_dir).column("k").is_sorted


def test_build_dictionary_types():
    d, ids = build_dictionary(np.array([3, 1, 2, 1], dtype=np.int64), DataType.LONG)
    assert list(d.values) == [1, 2, 3]
    np.testing.assert_array_equal(ids, [2, 0, 1, 0])
    d2, ids2 = build_dictionary(["b", "a", "b"], DataType.STRING)
    assert list(d2.values) == ["a", "b"]
    np.testing.assert_array_equal(ids2, [1, 0, 1])


def test_mismatched_column_lengths_rejected(tmp_path):
    schema = Schema("t", [metric("a", DataType.INT), metric("b", DataType.INT)])
    with pytest.raises(ValueError, match="ragged"):
        SegmentBuilder(schema).build({"a": np.arange(3), "b": np.arange(4)},
                                     str(tmp_path), "t_0")
