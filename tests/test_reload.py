"""Segment reload / index management tests (SegmentPreProcessor analog).

Reference scenarios: SegmentPreProcessorTest (add/remove index on an existing
segment), reload-via-controller integration tests.
"""

import numpy as np
import pytest

from pinot_tpu.cluster.enclosure import QuickCluster
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.segment.preprocess import preprocess_segment
from pinot_tpu.table import IndexingConfig, TableConfig


@pytest.fixture()
def plain_segment(tmp_path):
    schema = Schema("ev", [dimension("country", DataType.STRING),
                           dimension("body", DataType.STRING),
                           metric("v", DataType.LONG)])
    rng = np.random.default_rng(41)
    n = 500
    cols = {
        "country": [["US", "DE", "JP"][i] for i in rng.integers(0, 3, n)],
        "body": [f"quick brown fox {i % 7}" for i in range(n)],
        "v": rng.integers(0, 100, n, dtype=np.int64),
    }
    seg_dir = SegmentBuilder(schema).build(cols, str(tmp_path), "ev_0")
    return schema, seg_dir, cols


class TestPreprocess:
    def test_add_indexes_in_place(self, plain_segment):
        schema, seg_dir, cols = plain_segment
        before = load_segment(seg_dir)
        assert before.column("country").inverted_index is None
        changes = preprocess_segment(seg_dir, IndexingConfig(
            inverted_index_columns=["country"],
            range_index_columns=["v"],
            bloom_filter_columns=["country"],
            text_index_columns=["body"]))
        assert any("added inverted" in c for c in changes)
        seg = load_segment(seg_dir)
        assert seg.column("country").inverted_index is not None
        assert seg.column("country").bloom_filter is not None
        assert seg.column("body").text_index is not None
        # range index only if v is dict-encoded; raw columns skip it safely
        if seg.column("v").has_dictionary:
            assert seg.column("v").range_index is not None
        # the new inverted index agrees with a scan
        inv = seg.column("country").inverted_index
        dict_id = seg.column("country").dictionary.index_of("US")
        want = sum(1 for c in cols["country"] if c == "US")
        assert len(inv.doc_ids_for(dict_id)) == want

    def test_idempotent(self, plain_segment):
        _, seg_dir, _ = plain_segment
        cfg = IndexingConfig(inverted_index_columns=["country"])
        assert preprocess_segment(seg_dir, cfg)
        assert preprocess_segment(seg_dir, cfg) == []

    def test_remove_indexes(self, plain_segment):
        _, seg_dir, _ = plain_segment
        preprocess_segment(seg_dir, IndexingConfig(inverted_index_columns=["country"]))
        changes = preprocess_segment(seg_dir, IndexingConfig())
        assert any("removed inverted" in c for c in changes)
        assert load_segment(seg_dir).column("country").inverted_index is None


def test_cluster_reload_applies_new_indexes(tmp_path):
    cluster = QuickCluster(num_servers=2, work_dir=str(tmp_path))
    schema = Schema("ev", [dimension("country", DataType.STRING),
                           metric("v", DataType.LONG)])
    cfg = TableConfig("ev")
    cluster.create_table(schema, cfg)
    rng = np.random.default_rng(43)
    n = 300
    cluster.ingest_columns(cfg, {
        "country": [["US", "DE"][i] for i in rng.integers(0, 2, n)],
        "v": rng.integers(0, 50, n, dtype=np.int64)})
    before = cluster.query(
        "SELECT country, COUNT(*) FROM ev GROUP BY country ORDER BY country LIMIT 10")

    # change the indexing config and trigger a cluster-wide reload
    cfg.indexing = IndexingConfig(inverted_index_columns=["country"],
                                  bloom_filter_columns=["country"])
    cluster.controller.update_table(cfg)

    loaded = [s for srv in cluster.servers
              for s in srv.tables["ev_OFFLINE"].acquire()]
    assert loaded, "servers must hold the segment"
    for seg in loaded:
        assert seg.column("country").inverted_index is not None
        assert seg.column("country").bloom_filter is not None
    after = cluster.query(
        "SELECT country, COUNT(*) FROM ev GROUP BY country ORDER BY country LIMIT 10")
    assert after.rows == before.rows


def test_schema_evolution_backfills_default_columns(tmp_path, ssb_schema):
    """Adding a schema column + reload backfills old segments with defaults
    (reference: SegmentPreProcessor DefaultColumnHandler) so queries over the
    new column work cluster-wide."""
    import numpy as np
    from conftest import make_ssb_columns
    from pinot_tpu.cluster import QuickCluster
    from pinot_tpu.schema import DataType, Schema, metric
    from pinot_tpu.table import TableConfig

    cluster = QuickCluster(num_servers=2, work_dir=str(tmp_path))
    cfg = TableConfig(ssb_schema.name, replication=1)
    cluster.create_table(ssb_schema, cfg)
    cluster.ingest_columns(cfg, make_ssb_columns(np.random.default_rng(3), 300))

    # evolve: add a metric column, push the schema, reload
    v2 = Schema(ssb_schema.name,
                list(ssb_schema.fields) + [metric("lo_tax", DataType.DOUBLE)],
                ssb_schema.primary_key_columns)
    cluster.controller.add_schema(v2)
    cluster.controller.reload_table(cfg.table_name_with_type)

    res = cluster.query("SELECT SUM(lo_tax), COUNT(*) FROM lineorder "
                        "WHERE lo_quantity >= 1")
    assert res.rows[0][1] == 300
    assert res.rows[0][0] == 0.0      # metric default null is 0
    res = cluster.query("SELECT lo_region, AVG(lo_tax) FROM lineorder "
                        "GROUP BY lo_region LIMIT 10")
    assert all(r[1] == 0.0 for r in res.rows)
    # new ingests naturally carry the column; old + new mix cleanly
    cols = make_ssb_columns(np.random.default_rng(4), 100)
    cols["lo_tax"] = np.full(100, 2.5)
    cluster.ingest_columns(cfg, cols)
    res = cluster.query("SELECT SUM(lo_tax) FROM lineorder WHERE lo_quantity >= 1")
    assert res.rows[0][0] == 250.0


def test_crc_stays_valid_after_deferred_index_removal(tmp_path):
    """CRC is recorded for the directory as it looks AFTER the reaper deletes
    deferred index files — verify-segment must pass post-reload."""
    import os
    import numpy as np
    from pinot_tpu.schema import Schema, dimension, metric
    from pinot_tpu.segment.preprocess import preprocess_segment
    from pinot_tpu.segment.writer import SegmentBuilder, SegmentGeneratorConfig
    from pinot_tpu.table import IndexingConfig
    from pinot_tpu.tools.segment import verify_segment

    schema = Schema("t", [dimension("c"), metric("v")])
    seg_dir = SegmentBuilder(schema, SegmentGeneratorConfig(
        inverted_index_columns=["c"])).build(
        {"c": ["a", "b"], "v": np.array([1.0, 2.0])}, str(tmp_path), "t_0")
    deferred = []
    changes = preprocess_segment(seg_dir, IndexingConfig(),  # drop the index
                                 defer_removals=deferred)
    assert any("removed inverted" in c for c in changes)
    assert deferred
    for p in deferred:        # the reaper's deletion
        if os.path.exists(p):
            os.remove(p)
    report = verify_segment(seg_dir)
    assert report["ok"], report


def test_backfilled_column_with_index_first_reload(tmp_path, ssb_schema):
    """Regression: schema adds a column that the indexing config ALSO wants
    indexed — the index build on the first reload must see the backfilled
    column (metadata is persisted before load_segment re-reads it)."""
    import numpy as np
    from conftest import make_ssb_columns
    from pinot_tpu.cluster import QuickCluster
    from pinot_tpu.schema import DataType, Schema, dimension
    from pinot_tpu.table import IndexingConfig, TableConfig

    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    cfg = TableConfig(ssb_schema.name, replication=1)
    cluster.create_table(ssb_schema, cfg)
    cluster.ingest_columns(cfg, make_ssb_columns(np.random.default_rng(6), 200))

    v2 = Schema(ssb_schema.name,
                list(ssb_schema.fields) + [dimension("lo_channel", DataType.STRING)],
                ssb_schema.primary_key_columns)
    cluster.controller.add_schema(v2)
    cfg.indexing = IndexingConfig(inverted_index_columns=["lo_channel"])
    cluster.controller.update_table(cfg)
    changes = cluster.controller.reload_table(cfg.table_name_with_type)
    flat = "\n".join(str(c) for c in (changes or []))
    assert "ERROR" not in flat, flat

    res = cluster.query("SELECT COUNT(*) FROM lineorder WHERE lo_channel = 'null'")
    assert res.rows[0][0] == 200  # string default fill is 'null'


def test_deferred_removal_reaped_even_when_reload_errors(tmp_path):
    """Regression: when a reload both defers an index removal and fails a later
    step, the deferred files must still be reaped — the recorded CRC already
    excludes them."""
    import os
    import time
    import numpy as np
    from pinot_tpu.cluster.catalog import Catalog
    from pinot_tpu.cluster.deepstore import LocalDeepStore
    from pinot_tpu.cluster import QuickCluster
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.table import IndexingConfig, TableConfig
    from pinot_tpu.tools.segment import verify_segment

    schema = Schema("t2", [dimension("c"), metric("v")])
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    cfg = TableConfig("t2", replication=1,
                      indexing=IndexingConfig(inverted_index_columns=["c"]))
    cluster.create_table(schema, cfg)
    cluster.ingest_columns(cfg, {"c": ["a", "b", "a"],
                                 "v": np.array([1.0, 2.0, 3.0])})

    # evolve the schema with a new indexed column AND drop the old index; break
    # the new-index build by adding a bogus schema field type the builder can
    # handle but pointing the index at a column that will not exist on disk
    # drop the inverted index (deferred removal) and request a new index in the
    # same pass, with the index BUILD forced to fail after the removal was
    # already deferred
    import pinot_tpu.segment.preprocess as pp

    cfg.indexing = IndexingConfig(json_index_columns=["c"])
    cluster.controller.update_table(cfg, reload=False)
    orig_build = pp._build_index

    def failing_build(idx, seg, name, col_meta, prefix):
        raise RuntimeError("forced index-build failure")

    pp._build_index = failing_build
    try:
        changes = cluster.servers[0].reload_table(cfg.table_name_with_type)
    finally:
        pp._build_index = orig_build
    flat = "\n".join(str(c) for c in (changes or []))
    assert "ERROR" in flat, flat

    # the deferred old-index file must eventually be gone and CRC must verify
    server = cluster.servers[0]
    seg_dirs = []
    mgr = server._table_manager(cfg.table_name_with_type)
    segs = mgr.acquire()
    try:
        seg_dirs = [s.path for s in segs if getattr(s, "path", None)]
    finally:
        mgr.release(segs)
    deadline = time.time() + 6
    while time.time() < deadline:
        leftovers = [p for d in seg_dirs
                     for p in [os.path.join(d, "cols", "c.inv.npz")]
                     if os.path.exists(p)]
        if not leftovers:
            break
        time.sleep(0.1)
    for d in seg_dirs:
        report = verify_segment(d)
        assert report["ok"], report
