"""Segment reload / index management tests (SegmentPreProcessor analog).

Reference scenarios: SegmentPreProcessorTest (add/remove index on an existing
segment), reload-via-controller integration tests.
"""

import numpy as np
import pytest

from pinot_tpu.cluster.enclosure import QuickCluster
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.segment.preprocess import preprocess_segment
from pinot_tpu.table import IndexingConfig, TableConfig


@pytest.fixture()
def plain_segment(tmp_path):
    schema = Schema("ev", [dimension("country", DataType.STRING),
                           dimension("body", DataType.STRING),
                           metric("v", DataType.LONG)])
    rng = np.random.default_rng(41)
    n = 500
    cols = {
        "country": [["US", "DE", "JP"][i] for i in rng.integers(0, 3, n)],
        "body": [f"quick brown fox {i % 7}" for i in range(n)],
        "v": rng.integers(0, 100, n, dtype=np.int64),
    }
    seg_dir = SegmentBuilder(schema).build(cols, str(tmp_path), "ev_0")
    return schema, seg_dir, cols


class TestPreprocess:
    def test_add_indexes_in_place(self, plain_segment):
        schema, seg_dir, cols = plain_segment
        before = load_segment(seg_dir)
        assert before.column("country").inverted_index is None
        changes = preprocess_segment(seg_dir, IndexingConfig(
            inverted_index_columns=["country"],
            range_index_columns=["v"],
            bloom_filter_columns=["country"],
            text_index_columns=["body"]))
        assert any("added inverted" in c for c in changes)
        seg = load_segment(seg_dir)
        assert seg.column("country").inverted_index is not None
        assert seg.column("country").bloom_filter is not None
        assert seg.column("body").text_index is not None
        # range index only if v is dict-encoded; raw columns skip it safely
        if seg.column("v").has_dictionary:
            assert seg.column("v").range_index is not None
        # the new inverted index agrees with a scan
        inv = seg.column("country").inverted_index
        dict_id = seg.column("country").dictionary.index_of("US")
        want = sum(1 for c in cols["country"] if c == "US")
        assert len(inv.doc_ids_for(dict_id)) == want

    def test_idempotent(self, plain_segment):
        _, seg_dir, _ = plain_segment
        cfg = IndexingConfig(inverted_index_columns=["country"])
        assert preprocess_segment(seg_dir, cfg)
        assert preprocess_segment(seg_dir, cfg) == []

    def test_remove_indexes(self, plain_segment):
        _, seg_dir, _ = plain_segment
        preprocess_segment(seg_dir, IndexingConfig(inverted_index_columns=["country"]))
        changes = preprocess_segment(seg_dir, IndexingConfig())
        assert any("removed inverted" in c for c in changes)
        assert load_segment(seg_dir).column("country").inverted_index is None


def test_cluster_reload_applies_new_indexes(tmp_path):
    cluster = QuickCluster(num_servers=2, work_dir=str(tmp_path))
    schema = Schema("ev", [dimension("country", DataType.STRING),
                           metric("v", DataType.LONG)])
    cfg = TableConfig("ev")
    cluster.create_table(schema, cfg)
    rng = np.random.default_rng(43)
    n = 300
    cluster.ingest_columns(cfg, {
        "country": [["US", "DE"][i] for i in rng.integers(0, 2, n)],
        "v": rng.integers(0, 50, n, dtype=np.int64)})
    before = cluster.query(
        "SELECT country, COUNT(*) FROM ev GROUP BY country ORDER BY country LIMIT 10")

    # change the indexing config and trigger a cluster-wide reload
    cfg.indexing = IndexingConfig(inverted_index_columns=["country"],
                                  bloom_filter_columns=["country"])
    cluster.controller.update_table(cfg)

    loaded = [s for srv in cluster.servers
              for s in srv.tables["ev_OFFLINE"].acquire()]
    assert loaded, "servers must hold the segment"
    for seg in loaded:
        assert seg.column("country").inverted_index is not None
        assert seg.column("country").bloom_filter is not None
    after = cluster.query(
        "SELECT country, COUNT(*) FROM ev GROUP BY country ORDER BY country LIMIT 10")
    assert after.rows == before.rows
