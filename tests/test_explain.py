"""EXPLAIN PLAN tests: operator-tree output, plan-selection visibility
(device vs host vs star-tree vs metadata vs pruned), cluster + HTTP paths.

Reference pattern: ExplainPlanQueriesTest asserting [Operator, Operator_Id,
Parent_Id] rows for representative query shapes.
"""

import numpy as np
import pytest

from pinot_tpu.query.executor import execute_query
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.segment.reader import load_segment
from pinot_tpu.segment.writer import SegmentBuilder, SegmentGeneratorConfig

SCHEMA = Schema("ev", [
    dimension("site", DataType.STRING),
    metric("v", DataType.DOUBLE),
])


@pytest.fixture(scope="module")
def seg(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("explain")
    return load_segment(SegmentBuilder(SCHEMA, SegmentGeneratorConfig()).build(
        {"site": ["a", "b", "a", "c"], "v": np.array([1.0, 2.0, 3.0, 4.0])},
        str(tmp), "ev_0"))


def labels(res):
    return [r[0] for r in res.rows]


def tree_ok(res):
    """ids are pre-order, root parent is -1, every parent precedes its child."""
    ids = [r[1] for r in res.rows]
    assert ids == list(range(len(ids)))
    assert res.rows[0][2] == -1
    for _, op_id, parent in res.rows[1:]:
        assert 0 <= parent < op_id


def test_explain_device_group_by(seg):
    res = execute_query(
        [seg], "EXPLAIN PLAN FOR SELECT site, SUM(v) FROM ev "
               "WHERE site IN ('a', 'b') GROUP BY site")
    assert res.columns == ["Operator", "Operator_Id", "Parent_Id"]
    tree_ok(res)
    ls = labels(res)
    assert ls[0].startswith("BROKER_REDUCE")
    assert "COMBINE_GROUP_BY" in ls[1]
    assert any(l.startswith("SEGMENT_PLAN(segments:1)") for l in ls)
    assert any("DEVICE_FUSED_GROUP_BY" in l and "keys:site" in l for l in ls)
    assert any(l.startswith("FILTER_DICT") and "site" in l for l in ls)


def test_explain_host_fallback_visible(seg):
    res = execute_query(
        [seg], "EXPLAIN PLAN FOR SELECT UPPER(site), COUNT(*) FROM ev "
               "GROUP BY UPPER(site)")
    assert any("HOST_GROUP_BY" in l for l in labels(res))


def test_explain_metadata_and_pruned(seg):
    res = execute_query([seg], "EXPLAIN PLAN FOR SELECT COUNT(*) FROM ev")
    assert any("METADATA_ONLY_AGGREGATE" in l for l in labels(res))
    res = execute_query(
        [seg], "EXPLAIN PLAN FOR SELECT COUNT(*) FROM ev WHERE site = 'zzz'")
    assert any("PRUNED" in l for l in labels(res))


def test_explain_selection_order(seg):
    res = execute_query(
        [seg], "EXPLAIN PLAN FOR SELECT site, v FROM ev WHERE v > 1 "
               "ORDER BY v DESC LIMIT 2")
    ls = labels(res)
    assert "sort:[v DESC]" in ls[0] and "limit:2" in ls[0]
    assert any("SELECT_ORDERBY" in l for l in ls)
    assert any(l.startswith("FILTER_EXPR") for l in ls)


def test_explain_star_tree(tmp_path):
    from pinot_tpu.segment.startree import StarTreeIndexConfig
    cfg = SegmentGeneratorConfig(star_tree_configs=[StarTreeIndexConfig(
        dimensions_split_order=["site"], function_column_pairs=["SUM__v"])])
    seg = load_segment(SegmentBuilder(SCHEMA, cfg).build(
        {"site": ["a", "b"] * 50, "v": np.arange(100.0)}, str(tmp_path), "st_0"))
    res = execute_query(
        [seg], "EXPLAIN PLAN FOR SELECT site, SUM(v) FROM ev GROUP BY site")
    assert any(l.startswith("STAR_TREE_REWRITE") for l in labels(res))


def test_explain_identical_segments_collapse(tmp_path):
    segs = []
    for i in range(3):
        segs.append(load_segment(SegmentBuilder(SCHEMA).build(
            {"site": ["a", "b"], "v": np.array([1.0, 2.0])},
            str(tmp_path), f"m_{i}")))
    res = execute_query(
        segs, "EXPLAIN PLAN FOR SELECT site, COUNT(*) FROM ev GROUP BY site")
    assert any("SEGMENT_PLAN(segments:3)" in l for l in labels(res))


def test_explain_words_stay_valid_identifiers(tmp_path):
    """EXPLAIN/PLAN/FOR are contextual: columns with those names keep working."""
    schema = Schema("kw", [dimension("plan"), metric("v", DataType.DOUBLE)])
    seg = load_segment(SegmentBuilder(schema).build(
        {"plan": ["x", "y"], "v": np.array([1.0, 2.0])}, str(tmp_path), "kw_0"))
    res = execute_query([seg], "SELECT plan, v FROM kw ORDER BY plan LIMIT 5")
    assert res.rows == [["x", 1.0], ["y", 2.0]]
    res = execute_query(
        [seg], "EXPLAIN PLAN FOR SELECT plan FROM kw WHERE plan = 'x'")
    assert any("FILTER_DICT" in l for l in labels(res))


def test_explain_join_does_not_execute(tmp_path):
    """EXPLAIN of a JOIN must return the stage plan, not run the join."""
    from pinot_tpu.cluster import QuickCluster
    from pinot_tpu.table import TableConfig
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    t1 = Schema("orders", [dimension("cust"), metric("amt", DataType.DOUBLE)])
    t2 = Schema("custs", [dimension("cust"), dimension("region")])
    cfg1 = cluster.create_table(t1, TableConfig("orders"))
    cfg2 = cluster.create_table(t2, TableConfig("custs"))
    cluster.ingest_columns(cfg1, {"cust": ["c1", "c2"], "amt": np.array([5.0, 7.0])})
    cluster.ingest_columns(cfg2, {"cust": ["c1", "c2"], "region": ["e", "w"]})
    res = cluster.query(
        "EXPLAIN PLAN FOR SELECT c.region, SUM(o.amt) FROM orders o "
        "JOIN custs c ON o.cust = c.cust GROUP BY c.region")
    ls = labels(res)
    assert ls[0] == "MULTISTAGE_REDUCE"
    assert any(l.startswith("HASH_JOIN(type:inner") for l in ls)
    assert sum(l.startswith("TABLE_SCAN") for l in ls) == 2
    tree_ok(res)


def test_explain_through_cluster(tmp_path, ssb_schema):
    from conftest import make_ssb_columns
    from pinot_tpu.cluster import QuickCluster
    from pinot_tpu.table import TableConfig
    cluster = QuickCluster(num_servers=2, work_dir=str(tmp_path))
    cfg = TableConfig(ssb_schema.name, replication=1)
    cluster.create_table(ssb_schema, cfg)
    cluster.ingest_columns(cfg, make_ssb_columns(np.random.default_rng(1), 500))
    res = cluster.query("EXPLAIN PLAN FOR SELECT lo_region, SUM(lo_revenue) "
                        "FROM lineorder GROUP BY lo_region")
    tree_ok(res)
    ls = labels(res)
    assert ls[0].startswith("BROKER_REDUCE")
    assert any("DEVICE_FUSED_GROUP_BY" in l for l in ls)
    assert any("table:lineorder_OFFLINE" in l for l in ls)
