"""Device-backed serving: broker-routed queries execute through the mesh
executor inside the server role (VERDICT r4 #1).

In-proc tests run the DeviceQueryPipeline against the conftest 8-device CPU
mesh — the same MeshQueryExecutor/shard_map path the TPU server runs — and
prove (a) served results match the host engine, (b) the device pipeline
actually executed them (pipeline stats + metrics counter), (c) concurrent
queries batch into shared fetches, (d) host fallback still answers shapes the
device can't plan. A ProcessCluster test proves the config wiring boots a
REAL server OS process in device mode and serves through a real broker.
Reference: ServerInstance.java:55,120-186 (engine inside the serving role),
BaseServerStarter.java:467-560 (readiness gating).
"""

import threading

import numpy as np
import pytest

from pinot_tpu.cluster import QuickCluster
from pinot_tpu.cluster.device_server import DEVICE_FALLBACK, DeviceQueryPipeline
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.table import StreamConfig, TableConfig, TableType

from conftest import make_ssb_columns


@pytest.fixture()
def device_cluster(tmp_path, ssb_schema):
    """QuickCluster whose single server routes partials through a device
    pipeline over the virtual CPU mesh."""
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    pipeline = DeviceQueryPipeline()
    cluster.servers[0].device_pipeline = pipeline
    rng = np.random.default_rng(9)
    cfg = TableConfig(ssb_schema.name)
    cluster.create_table(ssb_schema, cfg)
    for i in range(3):
        cluster.ingest_columns(cfg, make_ssb_columns(rng, 2000))
    yield cluster, pipeline
    pipeline.stop()


DEVICE_QUERIES = [
    # NOTE: COUNT(*) with no WHERE (or with a predicate the planner folds
    # to match-all via column min/max metadata) answers from metadata — no
    # scan, no device. Every query here forces a real scan.
    "SELECT COUNT(*) FROM lineorder WHERE lo_quantity >= 2",
    "SELECT lo_region, SUM(lo_revenue), COUNT(*) FROM lineorder "
    "GROUP BY lo_region ORDER BY lo_region LIMIT 10",
    "SELECT SUM(lo_extendedprice * lo_discount) FROM lineorder "
    "WHERE lo_discount BETWEEN 1 AND 3 AND lo_quantity < 25 LIMIT 5",
    "SELECT lo_brand, SUM(lo_revenue) FROM lineorder GROUP BY lo_brand "
    "ORDER BY SUM(lo_revenue) DESC LIMIT 9",
]


@pytest.mark.parametrize("sql", DEVICE_QUERIES)
def test_served_query_executes_on_device(device_cluster, sql):
    cluster, pipeline = device_cluster
    before = pipeline.dispatched
    res = cluster.query(sql)
    assert pipeline.dispatched == before + 1, \
        "query did not execute through the device pipeline"
    # differential: host-engine cluster answer over the same segments
    host = cluster.servers[0]
    saved, host.device_pipeline = host.device_pipeline, None
    try:
        want = cluster.query(sql)
    finally:
        host.device_pipeline = saved
    assert len(res.rows) == len(want.rows)
    for dr, hr in zip(res.rows, want.rows):
        for dv, hv in zip(dr, hr):
            if isinstance(dv, float):
                assert abs(dv - hv) <= 2e-3 * max(1.0, abs(hv))
            else:
                assert dv == hv


def test_device_metrics_counter(device_cluster):
    cluster, pipeline = device_cluster
    from pinot_tpu.utils.metrics import get_registry
    cluster.query("SELECT COUNT(*) FROM lineorder WHERE lo_quantity >= 2")
    snap = get_registry().snapshot()
    assert any(k.startswith("pinot_server_device_queries") for k in snap), \
        f"no device counter in {list(snap)[:10]}"


def test_concurrent_queries_batch(device_cluster):
    """Concurrent clients drain into shared device fetches: mean batch > 1."""
    cluster, pipeline = device_cluster
    warm = "SELECT COUNT(*) FROM lineorder WHERE lo_quantity >= 2"
    expect = cluster.query(warm).rows[0][0]  # also warms the kernel cache
    b0, d0 = pipeline.batches, pipeline.dispatched
    n_threads, per = 8, 4
    errs = []

    def client():
        try:
            for _ in range(per):
                r = cluster.query(warm)
                assert r.rows[0][0] == expect
        except Exception as e:  # pragma: no cover - surfaced below
            errs.append(e)

    ts = [threading.Thread(target=client) for _ in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs
    dispatched = pipeline.dispatched - d0
    batches = pipeline.batches - b0
    assert dispatched == n_threads * per
    assert batches < dispatched, \
        f"no batching: {batches} batches for {dispatched} queries"


def test_host_fallback_for_selection(device_cluster):
    """Selection queries are pre-screened on the handler thread: they never
    enter the device pipeline (no batch-window wait) and the host path
    answers."""
    cluster, pipeline = device_cluster
    f0, d0 = pipeline.fallbacks, pipeline.dispatched
    res = cluster.query("SELECT lo_region, lo_revenue FROM lineorder "
                        "WHERE lo_quantity > 48 LIMIT 5")
    assert pipeline.fallbacks == f0 and pipeline.dispatched == d0, \
        "selection should bypass the pipeline entirely"
    assert len(res.rows) <= 5


def test_fallback_sentinel_direct():
    pipeline = DeviceQueryPipeline()
    try:
        from pinot_tpu.query.context import compile_query
        schema = Schema("t", [dimension("a", DataType.STRING),
                              metric("b", DataType.DOUBLE)])
        # no segments -> planning raises inside the loop -> DEVICE_FALLBACK
        ctx = compile_query("SELECT COUNT(*) FROM t", schema)
        assert pipeline.execute_partial(ctx, []) is DEVICE_FALLBACK
    finally:
        pipeline.stop()


def test_realtime_consuming_rides_host_alongside_device(tmp_path):
    """A hybrid moment: committed segments answer on the device path while
    the in-progress consuming rows merge in from the host manager."""
    from pinot_tpu.ingest.stream import MemoryStream
    schema = Schema("ev", [dimension("site", DataType.STRING),
                           metric("clicks", DataType.LONG)])
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    pipeline = DeviceQueryPipeline()
    cluster.servers[0].device_pipeline = pipeline
    cfg = TableConfig("ev", table_type=TableType.REALTIME,
                      stream=StreamConfig(stream_type="memory", topic="ev_dev",
                                          flush_threshold_rows=40))
    cluster.create_realtime_table(schema, cfg, num_partitions=1)
    import json as _json
    stream = MemoryStream.get("ev_dev")
    for i in range(100):
        stream.produce(_json.dumps({"site": f"s{i % 4}", "clicks": 1}),
                       partition=0)
    table = cfg.table_name_with_type
    for _ in range(12):
        cluster.pump_realtime(table)
    res = cluster.query("SELECT COUNT(*) FROM ev")
    assert res.rows[0][0] == 100
    pipeline.stop()


def test_process_cluster_device_mode(tmp_path, ssb_schema):
    """REAL OS-process server in device mode behind a real broker: the
    /health endpoint's device stats prove the served path dispatched on the
    mesh executor inside the server process."""
    import json as _json
    import os
    import urllib.request

    from pinot_tpu.cluster.process import ProcessCluster
    from pinot_tpu.segment.writer import SegmentBuilder

    rng = np.random.default_rng(3)
    cols = make_ssb_columns(rng, 4000)
    with ProcessCluster(
            num_servers=1, work_dir=str(tmp_path),
            server_env={"PINOT_TPU_SERVER_DEVICE_ENABLED": "true"}) as cluster:
        cluster.controller.add_schema(ssb_schema)
        cfg = TableConfig(ssb_schema.name)
        cluster.controller.add_table(cfg)
        b = SegmentBuilder(ssb_schema)
        seg = b.build(cols, os.path.join(str(tmp_path), "b"), "lineorder_0")
        cluster.controller.upload_segment(cfg.table_name_with_type, seg)
        import time
        deadline = time.time() + 30
        while time.time() < deadline:
            r = cluster.query("SELECT COUNT(*) FROM lineorder")[
                "resultTable"]["rows"]
            if r and r[0][0] == 4000:
                break
            time.sleep(0.2)
        res = cluster.query("SELECT lo_region, COUNT(*) FROM lineorder "
                            "GROUP BY lo_region ORDER BY lo_region LIMIT 10")
        assert sum(r[1] for r in res["resultTable"]["rows"]) == 4000
        # the server process's health endpoint carries the pipeline stats
        with open(os.path.join(cluster.run_dir, "server_0.ready")) as f:
            url = _json.load(f)["url"]
        st = _json.loads(urllib.request.urlopen(f"{url}/health").read())
        # the group-by dispatched on device (the bare COUNT(*) probe answers
        # from metadata and counts as a fallback)
        assert st["device"]["dispatched"] >= 1, st
        assert st["device"]["batches"] >= 1, st


def test_served_high_card_groupby_differential(tmp_path, ssb_schema):
    """High-cardinality GROUP BY through the SERVED device path (the
    chunked kernel feeding an UNTRIMMED server partial that the broker
    reduces) must match numpy exactly."""
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    pipeline = DeviceQueryPipeline()
    cluster.servers[0].device_pipeline = pipeline
    rng = np.random.default_rng(21)
    cfg = TableConfig(ssb_schema.name)
    cluster.create_table(ssb_schema, cfg)
    all_cols = {k: [] for k in make_ssb_columns(rng, 1)}
    for i in range(2):
        cols = make_ssb_columns(rng, 30_000)
        for k, v in cols.items():
            all_cols[k].extend(list(v))
        cluster.ingest_columns(cfg, cols)
    d0 = pipeline.dispatched
    res = cluster.query("SELECT lo_custkey, SUM(lo_revenue), COUNT(*) "
                        "FROM lineorder GROUP BY lo_custkey "
                        "ORDER BY SUM(lo_revenue) DESC LIMIT 50")
    assert pipeline.dispatched == d0 + 1, "did not run on the device path"
    keys = np.asarray(all_cols["lo_custkey"])
    revs = np.asarray(all_cols["lo_revenue"], dtype=np.float64)
    sums = {}
    cnts = {}
    for k, v in zip(keys.tolist(), revs.tolist()):
        sums[k] = sums.get(k, 0.0) + v
        cnts[k] = cnts.get(k, 0) + 1
    want = sorted(sums.items(), key=lambda kv: -kv[1])[:50]
    assert len(res.rows) == 50
    for (gk, gs, gc), (wk, ws) in zip(res.rows, want):
        assert gk == wk and gc == cnts[wk]
        assert abs(gs - ws) <= 2e-3 * max(1.0, abs(ws)), (gk, gs, ws)
    pipeline.stop()


def test_upsert_table_bypasses_device(tmp_path):
    """Upsert tables need per-doc validity masks (host state): on a
    device-enabled server they must take the host path and stay correct."""
    import json as _json

    from pinot_tpu.ingest.stream import MemoryStream
    from pinot_tpu.table import UpsertConfig

    schema = Schema("ups", [dimension("pk", DataType.STRING),
                            metric("v", DataType.LONG),
                            metric("ts", DataType.LONG)])
    schema.primary_key_columns = ["pk"]
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    pipeline = DeviceQueryPipeline()
    cluster.servers[0].device_pipeline = pipeline
    cfg = TableConfig("ups", table_type=TableType.REALTIME,
                      upsert=UpsertConfig(mode="FULL"),
                      stream=StreamConfig(stream_type="memory",
                                          topic="ups_dev",
                                          flush_threshold_rows=1000))
    cluster.create_realtime_table(schema, cfg, num_partitions=1)
    stream = MemoryStream.get("ups_dev")
    for i in range(60):
        stream.produce(_json.dumps(
            {"pk": f"k{i % 20}", "v": i, "ts": i}), partition=0)
    for _ in range(8):
        cluster.pump_realtime(cfg.table_name_with_type)
    d0 = pipeline.dispatched
    res = cluster.query("SELECT COUNT(*), SUM(v) FROM ups WHERE ts >= 0")
    # 20 live rows (latest per pk: i in 40..59)
    assert res.rows[0][0] == 20
    assert res.rows[0][1] == sum(range(40, 60))
    assert pipeline.dispatched == d0, "upsert query must not ride the device"
    pipeline.stop()


# -- served ORDER-BY-limit via the fused device top-k -----------------------

TOPK_QUERIES = [
    "SELECT lo_orderkey, lo_revenue FROM lineorder "
    "WHERE lo_quantity >= 10 ORDER BY lo_revenue DESC LIMIT 7",
    "SELECT lo_orderkey, lo_extendedprice FROM lineorder "
    "ORDER BY lo_extendedprice LIMIT 12",
    # NOTE: ordering by lo_orderdate would fall back by design — yyyymmdd
    # ints exceed 2^24, past f32's exact-integer range for the score pass
    "SELECT lo_orderkey, lo_orderdate, lo_revenue FROM lineorder "
    "WHERE lo_discount BETWEEN 1 AND 3 ORDER BY lo_orderkey LIMIT 9",
]


def _host_answer(cluster, sql):
    host = cluster.servers[0]
    saved, host.device_pipeline = host.device_pipeline, None
    try:
        return cluster.query(sql)
    finally:
        host.device_pipeline = saved


@pytest.mark.parametrize("sql", TOPK_QUERIES)
def test_served_orderby_limit_executes_topk_on_device(device_cluster, sql):
    """ORDER-BY-limit selections ride the fused filter+top_k kernel through
    the REAL ServerNode path: dispatched (not fallback) and row-for-row
    equal to the host reducer (unique random doubles -> no tie ambiguity)."""
    cluster, pipeline = device_cluster
    d0, f0 = pipeline.dispatched, pipeline.fallbacks
    res = cluster.query(sql)
    assert pipeline.dispatched == d0 + 1, \
        "ORDER-BY-limit selection did not execute through the device pipeline"
    assert pipeline.fallbacks == f0, "device top-k fell back to host"
    want = _host_answer(cluster, sql)
    assert res.rows == want.rows


def test_served_orderby_tie_keys_match_host(tmp_path):
    """Heavy ties: device and host may break ties differently (both are
    valid per SQL), but the ordered KEY multiset and row count must agree,
    and every device row must exist in the table."""
    schema = Schema("tt", [dimension("id", DataType.LONG),
                           metric("grade", DataType.INT),
                           metric("score", DataType.DOUBLE)])
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    pipeline = DeviceQueryPipeline()
    cluster.servers[0].device_pipeline = pipeline
    cfg = TableConfig("tt")
    cluster.create_table(schema, cfg)
    rng = np.random.default_rng(5)
    n = 3000
    rows = {"id": np.arange(n, dtype=np.int64),
            "grade": rng.integers(0, 4, n).astype(np.int32),  # 4 values: ties
            "score": np.round(rng.uniform(0, 100, n), 2)}
    cluster.ingest_columns(cfg, rows)
    try:
        sql = "SELECT id, grade FROM tt ORDER BY grade DESC LIMIT 40"
        d0 = pipeline.dispatched
        res = cluster.query(sql)
        assert pipeline.dispatched == d0 + 1
        want = _host_answer(cluster, sql)
        assert len(res.rows) == len(want.rows) == 40
        assert [r[1] for r in res.rows] == [r[1] for r in want.rows]
        by_id = dict(zip(rows["id"].tolist(), rows["grade"].tolist()))
        for rid, rgrade in res.rows:
            assert by_id[rid] == rgrade
    finally:
        pipeline.stop()


def test_served_orderby_nan_falls_back_to_host(tmp_path):
    """NaN order keys poison lax.top_k comparisons: the kernel reports
    nanMatches and the pipeline resolves DEVICE_FALLBACK — the host reducer
    (NaN-as-null ordering) answers, and device/host agree by construction."""
    schema = Schema("nt", [dimension("id", DataType.LONG),
                           metric("score", DataType.DOUBLE)])
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    pipeline = DeviceQueryPipeline()
    cluster.servers[0].device_pipeline = pipeline
    cfg = TableConfig("nt")
    cluster.create_table(schema, cfg)
    rng = np.random.default_rng(6)
    n = 2000
    score = np.round(rng.uniform(0, 100, n), 2)
    score[rng.choice(n, 25, replace=False)] = np.nan
    cluster.ingest_columns(cfg, {"id": np.arange(n, dtype=np.int64),
                                 "score": score})
    try:
        sql = "SELECT id, score FROM nt ORDER BY score DESC LIMIT 10"
        f0 = pipeline.fallbacks
        res = cluster.query(sql)
        assert pipeline.fallbacks == f0 + 1, \
            "NaN order keys must force the host fallback"
        want = _host_answer(cluster, sql)
        assert res.rows == want.rows
    finally:
        pipeline.stop()


def test_served_orderby_nulls_parity(tmp_path):
    """Null cells reach BOTH reducers as the column's null fill (the stored
    sentinel), so device top-k and host sort place them identically —
    including under NULLS LAST, which only reorders genuine None keys that
    the selection path never produces."""
    schema = Schema("nl", [dimension("id", DataType.LONG),
                           metric("score", DataType.DOUBLE)])
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    pipeline = DeviceQueryPipeline()
    cluster.servers[0].device_pipeline = pipeline
    cfg = TableConfig("nl")
    cluster.create_table(schema, cfg)
    rng = np.random.default_rng(7)
    n = 1500
    vals = list(np.round(rng.uniform(1, 100, n), 2))
    for i in rng.choice(n, 30, replace=False):
        vals[int(i)] = None  # stored as the DOUBLE metric null fill (0.0)
    cluster.ingest_columns(cfg, {"id": np.arange(n, dtype=np.int64),
                                 "score": vals})
    try:
        for sql in (
                "SELECT id, score FROM nl ORDER BY score LIMIT 35",
                "SELECT id, score FROM nl ORDER BY score ASC NULLS LAST "
                "LIMIT 35",
                "SELECT id, score FROM nl ORDER BY score DESC NULLS LAST "
                "LIMIT 8"):
            d0, f0 = pipeline.dispatched, pipeline.fallbacks
            res = cluster.query(sql)
            assert pipeline.dispatched == d0 + 1, sql
            assert pipeline.fallbacks == f0, sql
            want = _host_answer(cluster, sql)
            assert [r[1] for r in res.rows] == [r[1] for r in want.rows], sql
    finally:
        pipeline.stop()


def test_served_stacked_same_shape_queries_one_launch(tmp_path, ssb_schema):
    """N concurrent same-plan-shape aggregations (different literals) share
    ONE traced executable and ONE stacked kernel launch, with differential
    correctness per query."""
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    pipeline = DeviceQueryPipeline(start=False)
    cluster.servers[0].device_pipeline = pipeline
    rng = np.random.default_rng(13)
    cfg = TableConfig(ssb_schema.name)
    cluster.create_table(ssb_schema, cfg)
    for _ in range(2):
        cluster.ingest_columns(cfg, make_ssb_columns(rng, 2500))
    try:
        thresholds = [5, 12, 24, 36, 12]  # duplicate 12 -> dedupe hit
        sqls = [("SELECT COUNT(*), SUM(lo_revenue) FROM lineorder "
                 f"WHERE lo_quantity >= {q}") for q in thresholds]
        results = [None] * len(sqls)

        def run(i):
            results[i] = cluster.query(sqls[i])

        ts = [threading.Thread(target=run, args=(i,)) for i in range(len(sqls))]
        for t in ts:
            t.start()
        import time
        deadline = time.time() + 10
        while pipeline._q.qsize() < len(sqls) and time.time() < deadline:
            time.sleep(0.01)
        pipeline.start()
        for t in ts:
            t.join(timeout=120)
        s = pipeline.stats()
        assert s["dispatched"] == len(sqls)
        assert s["launches"] == 1, s
        assert s["stackedLaunches"] == 1, s
        assert s["dedupeHits"] == 1, s
        host = cluster.servers[0]
        saved, host.device_pipeline = host.device_pipeline, None
        try:
            for i, sql in enumerate(sqls):
                want = cluster.query(sql)
                for dr, hr in zip(results[i].rows, want.rows):
                    for dv, hv in zip(dr, hr):
                        if isinstance(dv, float):
                            assert abs(dv - hv) <= 2e-3 * max(1.0, abs(hv))
                        else:
                            assert dv == hv
        finally:
            host.device_pipeline = saved
    finally:
        pipeline.stop()


def test_pipeline_stage_histograms_exported(device_cluster):
    """The stage timings ride the process metrics registry as Prometheus
    histograms — the /metrics body a scraper sees."""
    from pinot_tpu.utils.metrics import get_registry
    cluster, pipeline = device_cluster
    cluster.query("SELECT COUNT(*) FROM lineorder WHERE lo_quantity >= 2")
    text = get_registry().render_prometheus()
    for stage in ("queue_wait", "dispatch", "fetch"):
        name = f"pinot_server_device_pipeline_{stage}_ms"
        assert f"# TYPE {name} histogram" in text, name
        assert f'{name}_bucket{{le="+Inf"}}' in text, name
    st = pipeline.stats()
    assert st["stageMs"]["fetch"]["count"] >= 1
