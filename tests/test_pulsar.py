"""PulsarLite: Pulsar binary-wire broker + stream plugin (VERDICT r4 #6).

Covers the wire framing (magic + CRC-32C payload frames, BaseCommand
protobuf), producer/consumer round trips over real TCP, the reader-style
SEEK/FLOW consumption model, and a REALTIME TABLE consuming through the
plugin across OS processes (ProcessCluster servers connect to the broker
over TCP — the cross-process claim the reference makes for its pulsar
plugin). Ref: PulsarPartitionLevelConsumer.java.
"""

import json
import os
import time

import numpy as np
import pytest

from pinot_tpu.ingest.pulsarlite import (PulsarLiteBroker, PulsarLiteConsumer,
                                         PulsarLiteProducer, encode_frame,
                                         read_frame, _base_command, CONNECT)
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.table import StreamConfig, TableConfig, TableType


@pytest.fixture()
def broker():
    b = PulsarLiteBroker()
    yield b
    b.stop()


def test_produce_consume_roundtrip(broker):
    prod = PulsarLiteProducer(broker.service_url, "t0")
    offs = [prod.send(json.dumps({"i": i}).encode(), ts=1000 + i)
            for i in range(40)]
    assert offs == list(range(40))
    prod.close()
    cons = PulsarLiteConsumer(broker.service_url, "t0", 0)
    batch = cons.fetch(0, 25)
    assert [m.offset for m in batch.messages] == list(range(25))
    assert json.loads(batch.messages[7].value) == {"i": 7}
    assert batch.messages[7].timestamp_ms == 1007
    assert batch.next_offset == 25
    batch2 = cons.fetch(25, 100)
    assert [m.offset for m in batch2.messages] == list(range(25, 40))
    assert cons.latest_offset() == 40
    cons.close()


def test_seek_semantics(broker):
    prod = PulsarLiteProducer(broker.service_url, "t1")
    for i in range(30):
        prod.send(f"v{i}".encode())
    cons = PulsarLiteConsumer(broker.service_url, "t1", 0)
    cons.fetch(0, 10)
    # non-contiguous restart: the consumer must SEEK, not deliver stale rows
    batch = cons.fetch(20, 10)
    assert [m.offset for m in batch.messages] == list(range(20, 30))
    # rewind (replay) also works — reader semantics
    batch = cons.fetch(5, 3)
    assert [m.value for m in batch.messages] == ["v5", "v6", "v7"]
    cons.close()
    prod.close()


def test_empty_fetch_returns_quickly(broker):
    PulsarLiteProducer(broker.service_url, "t2").close()
    cons = PulsarLiteConsumer(broker.service_url, "t2", 0)
    t0 = time.perf_counter()
    batch = cons.fetch(0, 10, timeout_ms=100)
    assert batch.messages == [] and batch.next_offset == 0
    assert time.perf_counter() - t0 < 2.0
    cons.close()


def test_crc_rejects_corruption(broker):
    import socket
    import struct
    host, port = broker.host, broker.port
    s = socket.create_connection((host, port))
    s.sendall(encode_frame(_base_command(CONNECT, {1: "x", 4: 21})))
    read_frame(s)
    # hand-build a SEND frame with a flipped payload byte: CRC must fail
    from pinot_tpu.ingest.pulsarlite import MAGIC, PRODUCER, SEND, _msg
    s.sendall(encode_frame(_base_command(PRODUCER, {
        1: "persistent://public/default/t3-partition-0", 2: 1, 3: 1})))
    read_frame(s)
    cmd = _base_command(SEND, {1: 1, 2: 1})
    meta = _msg({1: "p", 2: 1, 3: 0})
    from pinot_tpu.ingest.kafka_wire import crc32c
    meta_part = struct.pack(">I", len(meta)) + meta + b"payload"
    crc = crc32c(meta_part)
    corrupted = meta_part[:-1] + b"X"
    frame = struct.pack(">II", 4 + len(cmd) + 2 + 4 + len(corrupted),
                        len(cmd)) + cmd + MAGIC + struct.pack(">I", crc) \
        + corrupted
    s.sendall(frame)
    # broker drops the connection on CRC mismatch
    import contextlib
    with contextlib.suppress(OSError):
        assert read_frame(s) is None
    s.close()


def test_realtime_table_consumes_via_pulsar_across_processes(tmp_path):
    """A REALTIME table in a real OS-process cluster consumes through the
    pulsar plugin: server processes dial the broker over TCP."""
    from pinot_tpu.cluster.process import ProcessCluster

    schema = Schema("pev", [dimension("site", DataType.STRING),
                            metric("clicks", DataType.LONG)])
    broker = PulsarLiteBroker()
    try:
        prod = PulsarLiteProducer(broker.service_url, "pulsar_ev")
        for i in range(300):
            prod.send(json.dumps({"site": f"s{i % 3}",
                                  "clicks": 1}).encode())
        prod.close()
        with ProcessCluster(num_servers=2, work_dir=str(tmp_path)) as cluster:
            cluster.controller.add_schema(schema)
            cfg = TableConfig(
                "pev", table_type=TableType.REALTIME,
                stream=StreamConfig(
                    stream_type="pulsar", topic="pulsar_ev",
                    properties={"serviceUrl": broker.service_url},
                    flush_threshold_rows=10_000))
            cluster.controller.add_table(cfg, num_partitions=1)
            # generous deadline: the suite shares ONE host core with every
            # role process, and the consume loop's 50ms poll stretches badly
            # under full-suite load (passes in ~4s standalone)
            deadline = time.time() + 150
            total = 0
            while time.time() < deadline:
                try:
                    r = cluster.query(
                        "SELECT COUNT(*), SUM(clicks) FROM pev")[
                        "resultTable"]["rows"]
                except Exception:
                    # broker's catalog mirror may not have synced the new
                    # table yet ("unknown table") — retry within deadline
                    time.sleep(0.3)
                    continue
                total = r[0][0] if r else 0
                if total == 300:
                    assert r[0][1] == 300
                    break
                time.sleep(0.3)
            assert total == 300, f"consumed {total}/300 via pulsar wire"
    finally:
        broker.stop()
