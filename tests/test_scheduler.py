"""Query scheduler / admission control / quota tests.

Reference scenarios: QuerySchedulerTest (FCFS + bounded capacity),
QueryQuotaManager tests (per-table QPS).
"""

import threading
import time

import pytest

from pinot_tpu.query.scheduler import (QueryQuotaManager, QueryRejectedError,
                                       QueryScheduler, QueryTimeoutError, TokenBucket)


class TestQueryScheduler:
    def test_runs_and_accounts(self):
        s = QueryScheduler(max_concurrent=2)
        assert s.submit("t", lambda: 41 + 1) == 42
        snap = s.stats.snapshot()
        assert snap["submitted"] == snap["completed"] == 1
        assert snap["rejected"] == 0 and snap["running"] == 0
        s.stop()

    def test_bounded_queue_rejects(self):
        s = QueryScheduler(max_concurrent=1, max_pending=1)
        release = threading.Event()
        started = threading.Event()

        def slow():
            started.set()
            release.wait(5)
            return "slow"

        results = []
        t1 = threading.Thread(target=lambda: results.append(s.submit("t", slow)))
        t1.start()
        started.wait(2)
        # occupy the single pending slot
        t2 = threading.Thread(target=lambda: results.append(
            s.submit("t", lambda: "queued")))
        t2.start()
        for _ in range(100):
            if s.stats.queued >= 1:
                break
            time.sleep(0.01)
        with pytest.raises(QueryRejectedError):
            s.submit("t", lambda: "overflow")
        release.set()
        t1.join(5)
        t2.join(5)
        assert sorted(results) == ["queued", "slow"]
        assert s.stats.rejected == 1
        s.stop()

    def test_timeout(self):
        s = QueryScheduler(max_concurrent=1, default_timeout_s=0.05)
        with pytest.raises(QueryTimeoutError):
            s.submit("t", lambda: time.sleep(1))
        assert s.stats.timed_out == 1
        s.stop()

    def test_per_table_share(self):
        s = QueryScheduler(max_concurrent=4, per_table_share=0.25)  # cap 1 per table
        release = threading.Event()
        started = threading.Event()

        def slow():
            started.set()
            release.wait(5)

        th = threading.Thread(target=lambda: s.submit("hot", slow))
        th.start()
        started.wait(2)
        with pytest.raises(QueryRejectedError):
            s.submit("hot", lambda: None)  # table at its share
        assert s.submit("cold", lambda: "ok") == "ok"  # other tables unaffected
        release.set()
        th.join(5)
        s.stop()

    def test_stopped_scheduler_rejects(self):
        s = QueryScheduler()
        s.stop()
        with pytest.raises(QueryRejectedError):
            s.submit("t", lambda: 1)


class TestTokenBucket:
    def test_burst_then_refill(self):
        now = [0.0]
        b = TokenBucket(rate_per_s=2, burst=2, clock=lambda: now[0])
        assert b.try_acquire() and b.try_acquire()
        assert not b.try_acquire()       # burst exhausted
        now[0] += 0.5                     # refills 1 token
        assert b.try_acquire()
        assert not b.try_acquire()


def test_broker_quota_rejects(tmp_path):
    import numpy as np
    from pinot_tpu.cluster.enclosure import QuickCluster
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.table import QuotaConfig, TableConfig

    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    schema = Schema("q", [dimension("d", DataType.STRING), metric("m", DataType.LONG)])
    cfg = TableConfig("q", quota=QuotaConfig(max_qps=2))  # burst 2 per broker
    cluster.create_table(schema, cfg)
    cluster.ingest_columns(cfg, {"d": ["a", "b"], "m": np.array([1, 2])})
    assert cluster.query("SELECT COUNT(*) FROM q LIMIT 1").rows[0][0] == 2
    assert cluster.query("SELECT COUNT(*) FROM q LIMIT 1").rows[0][0] == 2
    with pytest.raises(QueryRejectedError):
        cluster.query("SELECT COUNT(*) FROM q LIMIT 1")  # third within the burst


def test_server_scheduler_wired(tmp_path):
    import numpy as np
    from pinot_tpu.cluster.catalog import Catalog
    from pinot_tpu.cluster.controller import Controller
    from pinot_tpu.cluster.deepstore import LocalDeepStore
    from pinot_tpu.cluster.server import ServerNode
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.segment import SegmentBuilder
    from pinot_tpu.table import TableConfig
    import os

    catalog = Catalog()
    deepstore = LocalDeepStore(os.path.join(str(tmp_path), "ds"))
    controller = Controller("c0", catalog, deepstore, os.path.join(str(tmp_path), "c"))
    sched = QueryScheduler(max_concurrent=2)
    server = ServerNode("s0", catalog, deepstore, os.path.join(str(tmp_path), "s"),
                        scheduler=sched)
    schema = Schema("t", [dimension("d", DataType.STRING), metric("m", DataType.LONG)])
    controller.add_schema(schema)
    controller.add_table(TableConfig("t"))
    seg_dir = SegmentBuilder(schema).build(
        {"d": ["x", "y", "x"], "m": np.array([1, 2, 3])}, str(tmp_path / "b"), "t_0")
    controller.upload_segment("t_OFFLINE", seg_dir)
    res = server.execute_partial("t_OFFLINE", "SELECT COUNT(*) FROM t LIMIT 1", None)
    assert res.scalar[0] == 3
    assert sched.stats.completed == 1
    # OPTION(timeoutMs=...) flows into the scheduler budget
    with pytest.raises(QueryTimeoutError):
        slow_sched = QueryScheduler(max_concurrent=1)
        server.scheduler = slow_sched
        import pinot_tpu.cluster.server as srv_mod
        orig = server._execute_partial
        server._execute_partial = lambda *a, **k: (time.sleep(1), orig(*a, **k))[1]
        try:
            server.execute_partial("t_OFFLINE",
                                   "SELECT COUNT(*) FROM t LIMIT 1 OPTION(timeoutMs=50)",
                                   None)
        finally:
            server._execute_partial = orig
    sched.stop()
