"""ADLS Gen2 deep store: create/append/flush REST client + stub, native
rename, auth, cluster chaos — completing 4-scheme cloud-FS parity with the
reference (s3/gcs/hdfs/adls). Mirrors test_gcsstore.py's proof pattern.
Ref: ADLSGen2PinotFS.java."""

import json

import pytest

from pinot_tpu.cluster.adlsstore import AdlsDeepStoreFS, AdlsError, AdlsStub
from pinot_tpu.cluster.deepstore import create_fs
from pinot_tpu.schema import DataType, Schema, date_time, dimension, metric
from pinot_tpu.table import StreamConfig, TableConfig, TableType

from conftest import wait_until


@pytest.fixture
def stub():
    s = AdlsStub(filesystem="pinot", token="tok456")
    yield s
    s.stop()


def test_adls_fs_contract(stub, tmp_path):
    fs = create_fs(stub.spec())
    assert isinstance(fs, AdlsDeepStoreFS)
    fs.put_bytes(b"hello", "t/seg0.tar.gz")
    assert fs.get_bytes("t/seg0.tar.gz") == b"hello"
    assert fs.exists("t/seg0.tar.gz") and fs.exists("t")
    assert not fs.exists("t/nope")
    src = tmp_path / "blob"
    src.write_bytes(b"\x00\x01" * 500)
    fs.upload(str(src), "t/seg1.tar.gz")
    dst = tmp_path / "out" / "blob"
    fs.download("t/seg1.tar.gz", str(dst))
    assert dst.read_bytes() == src.read_bytes()
    fs.put_bytes(b"x", "t/sub/inner.bin")
    assert fs.listdir("t") == ["seg0.tar.gz", "seg1.tar.gz", "sub"]
    fs.move("t/seg0.tar.gz", "moved/seg0.tar.gz")
    assert not fs.exists("t/seg0.tar.gz")
    assert fs.get_bytes("moved/seg0.tar.gz") == b"hello"
    fs.delete("t")
    assert not fs.exists("t/seg1.tar.gz") and not fs.exists("t/sub/inner.bin")
    with pytest.raises(FileNotFoundError):
        fs.get_bytes("t/seg1.tar.gz")


def test_adls_write_protocol_is_create_append_flush(stub):
    """An un-flushed file must not be readable — the three-step protocol is
    real, not a single PUT in disguise."""
    fs = create_fs(stub.spec())
    key = "t/partial.bin"
    fs._call("PUT", fs._url(fs._key(key), resource="file"))
    fs._call("PATCH", fs._url(fs._key(key), action="append", position="0"),
             b"abc", {"Content-Type": "application/octet-stream"})
    # no flush yet: invisible
    assert not fs.exists(key)
    with pytest.raises(FileNotFoundError):
        fs.get_bytes(key)
    fs._call("PATCH", fs._url(fs._key(key), action="flush", position="3"))
    assert fs.get_bytes(key) == b"abc"
    # append at the wrong position is rejected (409), like real Gen2
    fs._call("PUT", fs._url(fs._key("t/p2"), resource="file"))
    with pytest.raises(AdlsError) as e:
        fs._call("PATCH", fs._url(fs._key("t/p2"), action="append",
                                  position="7"), b"zz")
    assert e.value.status == 409


def test_adls_auth_required(stub):
    fs = create_fs(stub.spec().replace("tok456", "WRONG"))
    with pytest.raises(AdlsError) as e:
        fs.put_bytes(b"x", "t/x")
    assert e.value.status == 401


def test_adls_native_rename(stub):
    fs = create_fs(stub.spec())
    fs.put_bytes(b"payload", "a/seg.tar.gz")
    before = dict(stub.files)
    fs.move("a/seg.tar.gz", "b/seg.tar.gz")
    assert fs.get_bytes("b/seg.tar.gz") == b"payload"
    assert not fs.exists("a/seg.tar.gz")
    new_key = [k for k in stub.files if k.endswith("b/seg.tar.gz")][0]
    old_key = [k for k in before if k.endswith("a/seg.tar.gz")][0]
    assert stub.files[new_key] is before[old_key]  # metadata move, no copy


def test_process_cluster_on_adls_with_outage_heals(tmp_path):
    """ProcessCluster storing realtime segments through adls://; an outage
    mid-stream commits via peer download and heals after recovery (the
    same chaos flow as s3/gcs/hdfs — one deep-store SPI, four cloud wires)."""
    from pinot_tpu.cluster.process import ProcessCluster
    from pinot_tpu.ingest.kafkalite import LogBrokerClient, LogBrokerServer

    stub = AdlsStub(filesystem="pinot")
    srv = LogBrokerServer()
    try:
        client = LogBrokerClient(srv.bootstrap)
        client.create_topic("at", 1)
        cfg_path = tmp_path / "cluster.conf"
        cfg_path.write_text(f"controller.deepstore={stub.spec('deepstore')}\n")
        schema = Schema("at", [
            dimension("u", DataType.STRING), metric("v", DataType.LONG),
            date_time("ts", DataType.LONG)])
        with ProcessCluster(num_servers=2, work_dir=str(tmp_path),
                            config_path=str(cfg_path)) as cluster:
            cluster.controller.add_schema(schema)
            cfg = TableConfig(
                "at", table_type=TableType.REALTIME, time_column="ts",
                replication=2,
                stream=StreamConfig(stream_type="kafkalite", topic="at",
                                    properties={"bootstrap": srv.bootstrap},
                                    flush_threshold_rows=25))
            cluster.controller.add_table(cfg, num_partitions=1)
            table = cfg.table_name_with_type

            def count():
                rows = cluster.query(
                    "SELECT COUNT(*) FROM at")["resultTable"]["rows"]
                return rows[0][0] if rows else 0

            for i in range(30):
                client.produce("at", json.dumps(
                    {"u": f"u{i % 3}", "v": i, "ts": 1700000000000 + i}))
            assert wait_until(lambda: count() == 30, timeout=60)

            def done_segments():
                metas = cluster.controller.segments_meta(table)["segments"]
                return {n: m for n, m in metas.items()
                        if m.get("status") == "DONE"}
            assert wait_until(lambda: len(done_segments()) >= 1, timeout=60)
            assert any(k.endswith(".tar.gz") for k in stub.files)

            stub.outage = True
            try:
                for i in range(30, 60):
                    client.produce("at", json.dumps(
                        {"u": f"u{i % 3}", "v": i, "ts": 1700000000000 + i}))
                assert wait_until(
                    lambda: any(str(m.get("download_path", "")).startswith(
                        "peer://") for m in done_segments().values()),
                    timeout=90), "commit must survive the ADLS outage"
                assert wait_until(lambda: count() == 60, timeout=60)
            finally:
                stub.outage = False
            assert wait_until(
                lambda: all(not str(m.get("download_path", "")).startswith(
                    "peer://") for m in done_segments().values()),
                timeout=120), "deep-store healing did not run"
    finally:
        srv.stop()
        stub.stop()


def test_adls_listing_paginates_and_sees_directories(stub):
    """The client must follow x-ms-continuation (the stub pages honestly)
    and exists() must count directory-only paths; listdir stays one-level."""
    fs = create_fs(stub.spec())
    fs.page_size = 3   # force several continuation hops
    for i in range(10):
        fs.put_bytes(b"x", f"big/s{i:02d}/inner.bin")
    assert fs.listdir("big") == [f"s{i:02d}" for i in range(10)]
    # 'big/s03' holds only a subpath -> a directory entry, no file at it
    assert fs.exists("big/s03")
    assert not fs.exists("big/s99")
    # recursive listing through pagination sees every file
    assert len(fs._list_paths("big")) == 10
