"""Completion-protocol + fault-plane chaos tests.

Reference pattern: `SegmentCompletionIntegrationTest` (scripted FSM races) and
ChaosMonkey scenarios — committer dies before/after commitStart, controller loses
its in-memory FSMs mid-protocol, a laggard replica discards and downloads the
committed copy. Every scenario ends with a differential query check: no data loss.

The graftfault section runs a dual-server cluster under seeded `FaultSchedule`s
and asserts the three robustness invariants:

(a) every query returns FULL correct results, or `partialResult=true`, or a
    typed error — never silently short rows;
(b) consuming partitions on a crashed server reassign to a live server and
    resume from the committed offset with no row loss or duplication;
(c) the cluster re-converges to healthy routing within a bounded number of
    failure-detector ticks after the dead server returns;

and that a whole scenario is deterministic across two runs of the same seed.
"""

import json
import time

import pytest

from pinot_tpu.cluster import QuickCluster
from pinot_tpu.cluster.catalog import ONLINE, STATUS_DONE
from pinot_tpu.cluster.completion import (CATCHUP, COMMIT, COMMIT_CONTINUE,
                                          COMMIT_SUCCESS, CompletionFSM, DISCARD,
                                          FAILED, HOLD, KEEP)
from pinot_tpu.ingest.stream import MemoryStream
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.table import StreamConfig, TableConfig, TableType
from pinot_tpu.utils import faults
from pinot_tpu.utils.faults import FaultInjected, FaultSchedule


@pytest.fixture(autouse=True)
def _reset_streams():
    MemoryStream.reset_all()
    faults.deactivate()
    yield
    faults.deactivate()
    MemoryStream.reset_all()


@pytest.fixture()
def events_schema():
    return Schema("events", [
        dimension("user", DataType.STRING),
        metric("value", DataType.DOUBLE),
    ])


def realtime_cluster(tmp_path, schema, replication=2, flush_rows=20,
                     num_partitions=1):
    cluster = QuickCluster(num_servers=2, work_dir=str(tmp_path))
    cfg = TableConfig("events", table_type=TableType.REALTIME,
                      replication=replication,
                      stream=StreamConfig(stream_type="memory", topic="events_topic",
                                          decoder="json",
                                          flush_threshold_rows=flush_rows))
    cluster.create_realtime_table(schema, cfg, num_partitions)
    return cluster, cfg


def produce(topic, partition, rows):
    stream = MemoryStream.get(topic)
    for row in rows:
        stream.produce(json.dumps(row), partition=partition)


# -- FSM-level crash scripts --------------------------------------------------

def test_committer_crash_before_commit_start():
    """The elected committer dies without ever calling commitStart; after the
    commit timeout a surviving replica is re-elected and commits."""
    fsm = CompletionFSM("seg", num_replicas=2, commit_timeout_s=0.05)
    assert fsm.on_consumed("s1", 50)["status"] == HOLD
    # s2 has the higher offset: elected, told to COMMIT... and then crashes
    assert fsm.on_consumed("s2", 100)["status"] == COMMIT
    time.sleep(0.1)
    # s1 re-reports after the timeout: the silent committer's stale offset is
    # struck so the re-election can land on a live server
    r = fsm.on_consumed("s1", 50)
    assert r["status"] == COMMIT and fsm.committer == "s1"
    assert fsm.on_commit_start("s1") == COMMIT_CONTINUE
    assert fsm.on_commit_end("s1", 50) == COMMIT_SUCCESS
    # the resurrected old committer cannot double-commit; it discards (its 100 >
    # the committed 50 means its local build diverges from the committed copy)
    assert fsm.on_commit_start("s2") == FAILED
    assert fsm.on_consumed("s2", 100)["status"] == DISCARD


def test_committer_crash_mid_commit():
    """Committer crashes AFTER commitStart (deep-store upload may be in flight);
    the COMMITTING state itself times out and another replica takes over."""
    fsm = CompletionFSM("seg", num_replicas=2, commit_timeout_s=0.05)
    fsm.on_consumed("s1", 100)
    r = fsm.on_consumed("s2", 100)   # tie: s2 wins (offset, name) order
    assert fsm.committer == "s2"
    assert fsm.on_commit_start("s2") == COMMIT_CONTINUE   # ...and s2 dies here
    time.sleep(0.1)
    r = fsm.on_consumed("s1", 100)
    assert r["status"] == COMMIT and fsm.committer == "s1"
    assert fsm.on_commit_start("s1") == COMMIT_CONTINUE
    # the zombie's late commitEnd must not be accepted
    assert fsm.on_commit_end("s2", 100) == FAILED
    assert fsm.on_commit_end("s1", 100) == COMMIT_SUCCESS
    # caught-up peer keeps its local build
    assert fsm.on_consumed("s2", 100)["status"] == KEEP


def test_commit_start_adoption_after_controller_restart():
    """Controller restarts between sending COMMIT and receiving commitStart: the
    rebuilt (HOLDING, committer-less) FSM adopts the in-flight committer —
    but ONLY a rebuilt FSM, and only for replica-set members."""
    fsm = CompletionFSM("seg", num_replicas=2, rebuilt=True,
                        replica_set=frozenset({"s1", "s2"}))
    # a server outside the replica set can never hijack the commit
    assert fsm.on_commit_start("rogue") == FAILED
    assert fsm.on_commit_start("s1") == COMMIT_CONTINUE
    assert fsm.committer == "s1"
    # a second replica racing commitStart after the failover loses
    assert fsm.on_commit_start("s2") == FAILED
    assert fsm.on_commit_end("s1", 80) == COMMIT_SUCCESS


def test_no_adoption_on_fresh_fsm():
    """A brand-new segment's FSM (not rebuilt from a restart) still requires a
    real election: commitStart without a prior COMMIT is rejected."""
    fsm = CompletionFSM("seg", num_replicas=2)
    assert fsm.on_commit_start("s1") == FAILED
    assert fsm.committer is None and fsm.state == "HOLDING"


def test_laggard_catchup_script():
    """CATCHUP drives a behind replica to the committer's offset before commit."""
    fsm = CompletionFSM("seg", num_replicas=2)
    fsm.on_consumed("s1", 90)
    r = fsm.on_consumed("s2", 100)
    assert fsm.committer == "s2"
    r = fsm.on_consumed("s1", 90)
    assert r["status"] == CATCHUP and r["offset"] == 100
    r = fsm.on_consumed("s1", 100)   # caught up: parks until the commit lands
    assert r["status"] == HOLD


# -- cluster-level chaos ------------------------------------------------------

def test_controller_restart_between_commit_start_and_end(tmp_path, events_schema):
    """Controller loses its FSMs while the committer is building the segment
    (between commitStart and commitEnd): the rebuilt FSM adopts the in-flight
    committer at commitEnd instead of FAILing it into terminal ERROR."""
    cluster, cfg = realtime_cluster(tmp_path, events_schema, flush_rows=20,
                                    replication=1)
    table = cfg.table_name_with_type
    produce("events_topic", 0, [{"user": f"u{i}", "value": 1.0}
                                for i in range(25)])
    mgr0 = cluster.servers[0].realtime_manager(table)
    mgr1 = cluster.servers[1].realtime_manager(table)
    mgr = mgr0 if mgr0.consumers else mgr1   # replication=1: one server consumes
    mgr.pump_all()
    consumer = next(iter(mgr.consumers.values()))
    orig_build = consumer.build_immutable

    def build_during_restart():
        cluster.controller.llc.fsms.clear()   # the restart happens mid-build
        return orig_build()
    consumer.build_immutable = build_during_restart

    mgr.complete_all()   # single replica: elected immediately -> COMMIT -> build
    done = [m for m in cluster.catalog.segments[table].values()
            if m.status == STATUS_DONE]
    assert len(done) == 1, "commitEnd after FSM loss must adopt, not FAIL"
    assert int(done[0].end_offset) == 25
    assert cluster.query("SELECT COUNT(*) FROM events").rows[0][0] == 25

def test_controller_restart_mid_consumption(tmp_path, events_schema):
    """Losing every in-memory FSM mid-protocol (controller restart) must not
    strand the segment: FSMs rebuild from catalog metadata and the commit
    completes with no data loss."""
    cluster, cfg = realtime_cluster(tmp_path, events_schema, flush_rows=20)
    table = cfg.table_name_with_type
    produce("events_topic", 0, [{"user": f"u{i}", "value": float(i)}
                                for i in range(25)])
    cluster.pump_realtime(table)          # consume; end criteria reached
    before = cluster.query("SELECT COUNT(*) FROM events").rows[0][0]
    assert before == 25

    # "restart": the durable catalog survives, the in-memory FSMs do not
    cluster.controller.llc.fsms.clear()

    for _ in range(4):
        cluster.pump_realtime(table)
    metas = cluster.catalog.segments[table]
    done = [m for m in metas.values() if m.status == STATUS_DONE]
    assert len(done) == 1, "commit must complete after FSM loss"
    assert int(done[0].end_offset) == 25
    # differential: every row still answers
    assert cluster.query("SELECT COUNT(*) FROM events").rows[0][0] == 25


def test_replica_divergence_download_from_deepstore(tmp_path, events_schema):
    """One replica never consumes; after the other commits, the laggard serves
    the committed copy from the deep store — both replicas answer identically."""
    cluster, cfg = realtime_cluster(tmp_path, events_schema, flush_rows=20,
                                    replication=2)
    table = cfg.table_name_with_type
    produce("events_topic", 0, [{"user": f"u{i}", "value": 1.0}
                                for i in range(25)])

    # only server_0 consumes; server_1 is wedged (paused process)
    mgr0 = cluster.servers[0].realtime_manager(table)
    mgr0.pump_all()
    mgr0.complete_all()      # first consumed report -> HOLD (1/2 replicas)
    mgr0.complete_all()      # re-report -> elected -> COMMIT -> committed
    metas = cluster.catalog.segments[table]
    done = [m for m in metas.values() if m.status == STATUS_DONE]
    assert len(done) == 1
    committed = done[0]

    # ideal-state flip drove BOTH replicas ONLINE; the laggard (which had
    # nothing) must have downloaded the committed copy from the deep store
    ev = cluster.catalog.external_view[table]
    assert set(ev[committed.name].values()) == {ONLINE}
    assert committed.name in cluster.servers[1].segments_served(table)

    # differential: each replica alone answers the full committed data
    cluster.kill_server("server_0")
    assert cluster.query("SELECT COUNT(*) FROM events").rows[0][0] == 25
    cluster.revive_server("server_0")
    cluster.kill_server("server_1")
    assert cluster.query("SELECT COUNT(*) FROM events").rows[0][0] == 25


def test_dead_replica_consuming_segment_reassigned(tmp_path, events_schema):
    """Every replica of a CONSUMING segment dies; the validation manager moves
    it to a live server which re-consumes from the durable start offset."""
    cluster, cfg = realtime_cluster(tmp_path, events_schema, flush_rows=100,
                                    replication=1)
    table = cfg.table_name_with_type
    produce("events_topic", 0, [{"user": f"u{i}", "value": 1.0}
                                for i in range(10)])
    cluster.pump_realtime(table)

    # find the server consuming partition 0 and kill it
    seg_name = next(iter(cluster.controller.llc.fsms))
    holder = next(iter(cluster.catalog.ideal_state[table][seg_name]))
    cluster.kill_server(holder)

    # one validation round: segment reassigned to a live server as CONSUMING
    out = cluster.controller.llc.validate()
    assert seg_name in out["reassigned"], out
    new_assignment = cluster.catalog.ideal_state[table][seg_name]
    assert holder not in new_assignment
    assert all(st == "CONSUMING" for st in new_assignment.values())

    # the new replica re-consumes from the start offset: no data loss
    cluster.pump_realtime(table)
    survivor = next(iter(new_assignment))
    node = next(s for s in cluster.servers if s.instance_id == survivor)
    rt = node.realtime_manager(table)
    assert rt is not None and seg_name in rt.consumers
    assert rt.consumers[seg_name].mutable.num_docs == 10
    # a validation round with everyone alive is a no-op
    assert cluster.controller.llc.validate()["reassigned"] == []


def test_committer_crash_cluster_level(tmp_path, events_schema):
    """The elected committer server is killed before it can commit; the second
    replica takes over after the commit timeout and no rows are lost."""
    cluster, cfg = realtime_cluster(tmp_path, events_schema, flush_rows=20,
                                    replication=2)
    table = cfg.table_name_with_type
    produce("events_topic", 0, [{"user": f"u{i}", "value": 1.0}
                                for i in range(25)])
    mgr0 = cluster.servers[0].realtime_manager(table)
    mgr1 = cluster.servers[1].realtime_manager(table)
    mgr0.pump_all()
    mgr1.pump_all()

    # shrink the FSM's commit timeout so the test doesn't wait 120s
    seg_name = next(iter(cluster.controller.llc.fsms))
    fsm = cluster.controller.llc.fsms[seg_name]
    fsm.commit_timeout_s = 0.05

    # server_1 will win the (offset, name) tie-break; script its crash at the
    # exact moment it would commit — it receives COMMIT and then dies
    consumer1 = next(iter(mgr1.consumers.values()))
    consumer1._commit = lambda: None
    mgr0.complete_all()          # first report -> HOLD
    mgr1.complete_all()          # elected -> COMMIT -> "crash"
    assert fsm.committer == "server_1"
    assert not any(m.status == STATUS_DONE
                   for m in cluster.catalog.segments[table].values())
    time.sleep(0.1)

    # the survivor re-reports after the timeout, takes over, commits
    mgr0.complete_all()
    done = [m for m in cluster.catalog.segments[table].values()
            if m.status == STATUS_DONE]
    assert len(done) == 1
    assert int(done[0].end_offset) == 25
    assert fsm.committer == "server_0"
    assert cluster.query("SELECT COUNT(*) FROM events").rows[0][0] == 25


# -- graftfault: seeded fault-schedule chaos ----------------------------------

def _crash_scenario(work_dir, seed, queries=8):
    """One seeded `server.crash` run against a dual-server offline table;
    returns (per-query outcome labels, per-site fire counts). Asserts
    invariant (a) inline: full, flagged-partial, or typed error — never
    silently short rows."""
    from concurrent.futures import ThreadPoolExecutor

    cluster = QuickCluster(num_servers=2, work_dir=str(work_dir))
    schema = Schema("metrics", [dimension("user", DataType.STRING),
                                metric("value", DataType.DOUBLE)])
    cfg = cluster.create_table(schema)
    for seg in range(2):
        cluster.ingest_columns(cfg, {
            "user": [f"u{seg}_{i}" for i in range(50)],
            "value": [1.0] * 50})
    # narrow the scatter pool to ONE worker so dispatches execute in
    # submission order: the per-site RNG then sees the same draw sequence
    # every run (see the faults module docstring on strict determinism)
    cluster.broker._pool.shutdown(wait=True)
    cluster.broker._pool = ThreadPoolExecutor(max_workers=1)

    outcomes = []
    sched = FaultSchedule({"server.crash": {"p": 0.5}}, seed=seed)
    with faults.active(sched):
        for _ in range(queries):
            # each query starts from a clean routing view: a crash-injected
            # server was marked unhealthy by the broker taxonomy, and this
            # is the operator/detector re-admitting it between queries
            for s in cluster.servers:
                cluster.revive_server(s.instance_id)
                cluster.broker.failure_detector.notify_healthy(s.instance_id)
            try:
                res = cluster.query("SELECT COUNT(*) FROM metrics")
            except Exception as e:
                # invariant (a): an error outcome must be TYPED, not a bare
                # short answer — the exception class is the type
                outcomes.append(f"error:{type(e).__name__}")
                continue
            total = res.rows[0][0]
            if res.stats["partialResult"]:
                assert total <= 100
                outcomes.append("partial")
            else:
                assert total == 100, \
                    f"silent short rows: {total}/100 without partialResult"
                outcomes.append("full")
    return outcomes, sched.fired()


def test_seeded_crash_schedule_invariants_and_determinism(tmp_path):
    """Invariant (a) under a seeded 50%-crash schedule, plus determinism:
    two runs of the same seed produce the same per-query outcome sequence
    and the same per-site fire counts."""
    run_a = _crash_scenario(tmp_path / "a", seed=1234)
    run_b = _crash_scenario(tmp_path / "b", seed=1234)
    assert run_a == run_b
    outcomes, fired = run_a
    assert fired.get("server.crash", 0) > 0, \
        "the schedule never fired: the scenario tested nothing"
    # the 50% schedule must have produced BOTH behaviors at this seed, or
    # the invariant assertions above were vacuous
    assert "full" in outcomes and "partial" in outcomes, outcomes


def test_consuming_reassignment_under_stream_faults(tmp_path, events_schema):
    """Invariant (b): under injected stream stalls + a lost partition, the
    consume path retries from its committed offset (no loss, no duplication),
    and killing the consuming server reassigns the partition to the live
    server which resumes exactly."""
    cluster, cfg = realtime_cluster(tmp_path, events_schema, flush_rows=100,
                                    replication=1)
    table = cfg.table_name_with_type
    produce("events_topic", 0, [{"user": f"u{i}", "value": 1.0}
                                for i in range(30)])

    sched = FaultSchedule({
        # two lost-partition faults, then the stream "recovers"
        "stream.partition.lost": {"p": 1.0, "count": 2},
        # every later fetch is merely slow, not dead
        "stream.stall": {"latencyMs": 1.0, "count": 4},
    }, seed=7)
    with faults.active(sched):
        # drive the pump the way the production consume loop does: a raised
        # fault is caught, backed off, and retried from self.offset
        for _ in range(6):
            try:
                cluster.pump_realtime(table)
            except FaultInjected:
                continue
    assert sched.fired("stream.partition.lost") == 2
    assert cluster.query("SELECT COUNT(*) FROM events").rows[0][0] == 30

    # now the consuming server dies; the validation round must move the
    # partition to the live server, which re-consumes with no loss/dup
    seg_name = next(iter(cluster.controller.llc.fsms))
    holder = next(iter(cluster.catalog.ideal_state[table][seg_name]))
    cluster.kill_server(holder)
    moved = cluster.controller.llc.reassign_dead_consuming_segments()
    assert seg_name in moved
    new_assignment = cluster.catalog.ideal_state[table][seg_name]
    assert holder not in new_assignment
    # fresh election on the reassigned segment: no stale committer state
    fsm = cluster.controller.llc.fsms[seg_name]
    assert fsm.state == "HOLDING" and fsm.committer is None

    produce("events_topic", 0, [{"user": f"w{i}", "value": 1.0}
                                for i in range(10)])
    for _ in range(3):
        cluster.pump_realtime(table)
    assert cluster.query("SELECT COUNT(*) FROM events").rows[0][0] == 40, \
        "reassigned partition lost or duplicated rows"


def test_failure_detector_reconvergence_bounded_ticks(tmp_path, events_schema):
    """Invariant (c): after a killed server comes back, deterministic
    failure-detector ticks re-admit it to routing within a bounded count."""
    cluster = QuickCluster(num_servers=2, work_dir=str(tmp_path))
    detector = cluster.broker.failure_detector
    # QuickCluster wires in-proc handles with no probes; register the same
    # aliveness probe the HTTP services wire up (GET /health analog)
    for s in cluster.servers:
        detector.register_probe(
            s.instance_id,
            lambda sid=s.instance_id: cluster.catalog.instances[sid].alive)

    cluster.kill_server("server_0")
    detector.notify_unhealthy("server_0")
    assert detector.snapshot()["server_0"]["state"] == "probing"

    # dead: ticks keep failing, the probe interval backs off, and the
    # consecutive-failure count grows monotonically
    now = time.time()
    for i in range(3):
        now += 40.0   # larger than max_interval_s: every tick is "due"
        detector.tick(now=now)
    snap = detector.snapshot()["server_0"]
    assert snap["state"] == "probing" and snap["consecutiveFailures"] == 3
    assert "server_0" in cluster.broker.routing.unhealthy_servers()

    # revive the process (catalog alive flag) but NOT the routing entry:
    # only a successful probe may re-admit it
    cluster.catalog.set_instance_alive("server_0", True)
    ticks_to_heal = 0
    for _ in range(4):
        now += 40.0
        ticks_to_heal += 1
        detector.tick(now=now)
        if "server_0" not in cluster.broker.routing.unhealthy_servers():
            break
    assert ticks_to_heal == 1, \
        f"re-convergence took {ticks_to_heal} ticks (bound: 1 once due)"
    assert detector.snapshot()["server_0"] == {
        "state": "healthy", "consecutiveFailures": 0}


def test_hedged_request_wins_and_never_double_counts(tmp_path):
    """A straggling primary (injected `server.slow`) is hedged onto the other
    replica; the hedge answers, the query stays non-partial, and the merged
    stats count the segment ONCE (the loser's partial is dropped unmerged)."""
    cluster = QuickCluster(num_servers=2, work_dir=str(tmp_path))
    schema = Schema("metrics", [dimension("user", DataType.STRING),
                                metric("value", DataType.DOUBLE)])
    cfg = cluster.create_table(
        schema, TableConfig("metrics", replication=2))
    cluster.ingest_columns(cfg, {"user": [f"u{i}" for i in range(40)],
                                 "value": [1.0] * 40})
    cluster.catalog.put_property("clusterConfig/broker.hedge.enabled", "true")
    cluster.catalog.put_property("clusterConfig/broker.hedge.delay.ms", "20")

    # budget of ONE slow fault: the primary dispatch eats it and stalls;
    # the hedge dispatch crosses the same site with the budget spent and
    # runs at full speed — first response wins
    sched = FaultSchedule({"server.slow": {"latencyMs": 400, "count": 1}},
                          seed=3)
    with faults.active(sched):
        t0 = time.monotonic()
        res = cluster.query("SELECT COUNT(*) FROM metrics")
        elapsed = time.monotonic() - t0
    assert res.rows[0][0] == 40
    assert not res.stats["partialResult"]
    assert res.stats["hedgedRequests"] == 1
    assert sched.fired("server.slow") == 1
    # the segment was served by BOTH sides of the hedged unit but merged
    # exactly once — the numSegmentsQueried invariant
    assert res.stats["numSegmentsQueried"] == 1
    assert res.stats["numServersQueried"] == 1
    assert elapsed < 0.4, \
        f"hedge did not cut the straggler latency (took {elapsed:.3f}s)"


def test_hedging_disabled_by_default(tmp_path):
    """Without the knob, a slow server is simply waited out — no hedges, no
    hedgedRequests stat movement."""
    cluster = QuickCluster(num_servers=2, work_dir=str(tmp_path))
    schema = Schema("metrics", [dimension("user", DataType.STRING),
                                metric("value", DataType.DOUBLE)])
    cfg = cluster.create_table(
        schema, TableConfig("metrics", replication=2))
    cluster.ingest_columns(cfg, {"user": [f"u{i}" for i in range(10)],
                                 "value": [1.0] * 10})
    sched = FaultSchedule({"server.slow": {"latencyMs": 50, "count": 1}},
                          seed=3)
    with faults.active(sched):
        res = cluster.query("SELECT COUNT(*) FROM metrics")
    assert res.rows[0][0] == 10
    assert res.stats["hedgedRequests"] == 0


# -- satellite coverage: committer-stale takeover + dead-server reassign ------

def test_can_adopt_committer_stale_takeover():
    """`can_adopt`/`adopt_committer` unit semantics: only a REBUILT, holding,
    committer-less FSM lets a replica-set member claim the in-flight commit;
    adoption installs it as committer in COMMITTING with a fresh clock."""
    fsm = CompletionFSM("seg", num_replicas=2, rebuilt=True,
                        replica_set=frozenset({"s1", "s2"}))
    assert not fsm.can_adopt("rogue")          # outside the replica set
    assert fsm.can_adopt("s1") and fsm.can_adopt("s2")

    before = time.time()
    fsm.adopt_committer("s2")
    assert fsm.committer == "s2" and fsm.state == "COMMITTING"
    assert fsm.committer_decided_at >= before  # stale clock restarted
    assert fsm.offsets["s2"] == -1             # placeholder until it reports
    # adoption is single-shot: with a committer installed nobody else adopts
    assert not fsm.can_adopt("s1") and not fsm.can_adopt("s2")
    assert fsm.on_commit_end("s2", 70) == COMMIT_SUCCESS

    # a fresh (non-rebuilt) FSM never adopts, whatever the claimant
    fresh = CompletionFSM("seg2", num_replicas=2,
                          replica_set=frozenset({"s1"}))
    assert not fresh.can_adopt("s1")


def test_reassign_dead_consuming_segments_direct(tmp_path, events_schema):
    """`reassign_dead_consuming_segments` (called directly, as the validation
    manager does): a consuming segment whose only replica died moves to the
    live server with a reset FSM; segments with a live replica stay put."""
    cluster, cfg = realtime_cluster(tmp_path, events_schema, flush_rows=100,
                                    replication=1, num_partitions=2)
    table = cfg.table_name_with_type
    for p in range(2):
        produce("events_topic", p, [{"user": f"p{p}_{i}", "value": 1.0}
                                    for i in range(5)])
    cluster.pump_realtime(table)

    # two partitions, replication=1, two servers: one consuming segment per
    # server; kill server_0 and only ITS segment may move
    ist = cluster.catalog.ideal_state[table]
    victim_segs = [s for s, a in ist.items() if "server_0" in a]
    safe_segs = [s for s, a in ist.items() if "server_0" not in a]
    assert victim_segs and safe_segs, ist
    cluster.kill_server("server_0")

    moved = cluster.controller.llc.reassign_dead_consuming_segments()
    assert sorted(moved) == sorted(victim_segs)
    for seg in victim_segs:
        assignment = cluster.catalog.ideal_state[table][seg]
        assert assignment and "server_0" not in assignment
        assert all(st == "CONSUMING" for st in assignment.values())
        fsm = cluster.controller.llc.fsms[seg]
        assert fsm.state == "HOLDING" and fsm.committer is None
    for seg in safe_segs:
        assert cluster.catalog.ideal_state[table][seg] == ist[seg]
    # idempotent: nothing left to move
    assert cluster.controller.llc.reassign_dead_consuming_segments() == []

    # the survivor picks the moved partition up; no rows lost
    cluster.pump_realtime(table)
    assert cluster.query("SELECT COUNT(*) FROM events").rows[0][0] == 10


# -- graftfault x admission: overload combined with a fault schedule ----------

def _overload_chaos_scenario(work_dir, seed, queries=12):
    """Deterministic overload-under-faults lane: the broker is pinned in
    SHEDDING (queue.high=1 makes every query's own begin() tip the depth
    signal) while a seeded `server.slow` + `server.crash` schedule batters the
    scatter path. Expensive scans shed typed; cheap aggregations ride the
    served path through the stragglers and crashes. Returns (per-query
    outcome labels, per-site fire counts)."""
    from concurrent.futures import ThreadPoolExecutor

    from pinot_tpu.query.scheduler import QueryRejectedError

    cluster = QuickCluster(num_servers=2, work_dir=str(work_dir))
    schema = Schema("metrics", [dimension("user", DataType.STRING),
                                metric("value", DataType.DOUBLE)])
    cfg = cluster.create_table(schema, TableConfig("metrics", replication=2))
    for seg in range(2):
        cluster.ingest_columns(cfg, {
            "user": [f"u{seg}_{i}" for i in range(50)],
            "value": [1.0] * 50})
    # single scatter worker: dispatches execute in submission order so the
    # per-site RNGs see the same draw sequence every run (strict determinism)
    cluster.broker._pool.shutdown(wait=True)
    cluster.broker._pool = ThreadPoolExecutor(max_workers=1)
    cluster.catalog.put_property("clusterConfig/broker.admission.enabled",
                                 "true")
    cluster.catalog.put_property("clusterConfig/broker.admission.queue.high",
                                 "1")

    outcomes = []
    sched = FaultSchedule({"server.slow": {"p": 0.4, "latencyMs": 10},
                           "server.crash": {"p": 0.3}}, seed=seed)
    with faults.active(sched):
        for i in range(queries):
            for s in cluster.servers:
                cluster.revive_server(s.instance_id)
                cluster.broker.failure_detector.notify_healthy(s.instance_id)
            sql = ("SELECT user, value FROM metrics LIMIT 20000" if i % 2
                   else "SELECT COUNT(*) FROM metrics")
            try:
                res = cluster.query(sql)
            except QueryRejectedError as e:
                # a shed is typed AND labeled with its reason — record the
                # reason, not the message (whose hints vary run to run)
                msg = str(e)
                reason = msg[msg.index("(") + 1:msg.index(")")]
                outcomes.append(f"shed:{reason}")
                continue
            except Exception as e:
                outcomes.append(f"error:{type(e).__name__}")
                continue
            if res.stats["partialResult"]:
                assert res.rows[0][0] <= 100
                outcomes.append("partial")
            else:
                assert res.rows[0][0] == 100, \
                    f"silent short rows: {res.rows[0][0]}/100"
                outcomes.append("full")
    return outcomes, sched.fired()


def test_overload_chaos_lane_typed_outcomes_and_determinism(tmp_path):
    """Overload + seeded faults yields ONLY full / flagged-partial / typed
    outcomes, deterministically: two same-seed runs match query for query."""
    run_a = _overload_chaos_scenario(tmp_path / "a", seed=4242)
    run_b = _overload_chaos_scenario(tmp_path / "b", seed=4242)
    assert run_a == run_b
    outcomes, fired = run_a
    allowed = {"full", "partial", "shed:expensive", "shed:saturated"}
    for o in outcomes:
        assert o in allowed or o.startswith("error:"), outcomes
    # the lane is vacuous unless BOTH pressures actually fired: every
    # expensive scan shed while the shed-state machine held, and the fault
    # schedule bit the served path at least once
    assert outcomes.count("shed:expensive") == len(outcomes) // 2, outcomes
    assert fired.get("server.slow", 0) > 0 or \
        fired.get("server.crash", 0) > 0, fired
    assert "full" in outcomes, outcomes


# -- graftfault: tiered-storage chaos (memory pressure x download faults) -----

def _tiering_chaos_scenario(work_dir, seed, queries=10):
    """One seeded run of the tiered-storage lane: a 3-segment offline table
    pinned to ~1.3 device blocks of HBM capacity (constant admission/eviction
    churn), one segment re-demoted COLD before every query so the lazy
    deep-store reload keeps running, and a seeded `deepstore.download.fail`
    schedule biting those reloads. Returns (per-query outcome labels, fire
    counts). Asserts inline, per query: outcomes are full / flagged-partial /
    typed-error ONLY (never silent short rows, never OOM) and ledger
    residency never exceeds the pinned capacity."""
    from concurrent.futures import ThreadPoolExecutor

    from pinot_tpu.cluster.peers import clear_download_quarantine
    from pinot_tpu.engine.datablock import predicted_block_bytes
    from pinot_tpu.utils.memledger import get_ledger, reset_ledger
    from pinot_tpu.utils.metrics import get_registry

    reset_ledger()
    get_registry().reset()
    clear_download_quarantine()
    cluster = QuickCluster(num_servers=1, work_dir=str(work_dir))
    schema = Schema("metrics", [dimension("user", DataType.STRING),
                                metric("value", DataType.DOUBLE)])
    cfg = cluster.create_table(schema)
    table = cfg.table_name_with_type
    names = []
    for seg in range(3):
        names.append(cluster.ingest_columns(cfg, {
            "user": [f"u{seg}_{i}" for i in range(50)],
            "value": [1.0] * 50}))
    mgr = cluster.servers[0].tables[table]
    capacity = int(predicted_block_bytes(mgr.get(names[1])) * 1.3)
    get_ledger().set_capacity(capacity)
    # single-worker scatter pool: dispatches execute in submission order so
    # the per-site RNG sees the same draw sequence every run
    cluster.broker._pool.shutdown(wait=True)
    cluster.broker._pool = ThreadPoolExecutor(max_workers=1)

    outcomes = []
    sched = FaultSchedule({"deepstore.download.fail": {"p": 0.85}},
                          seed=seed)
    with faults.active(sched):
        for i in range(queries):
            # the operator/detector re-admits the server after an errored
            # query, and the blob leaves quarantine (store "recovered") —
            # then the segment is demoted cold again so THIS query has to
            # ride the faulted lazy-reload path
            cluster.revive_server("server_0")
            cluster.broker.failure_detector.notify_healthy("server_0")
            clear_download_quarantine()
            cluster.controller.demote_segment_to_cold(table, names[0])
            sql = ("SELECT SUM(value) FROM metrics" if i % 2
                   else "SELECT COUNT(*) FROM metrics")
            try:
                res = cluster.query(sql)
            except Exception as e:
                outcomes.append(f"error:{type(e).__name__}")
            else:
                total = res.rows[0][0]
                if res.stats["partialResult"]:
                    # a SUM partial that covered zero segments is None
                    assert total is None or total <= 150 + 1e-9
                    outcomes.append("partial")
                else:
                    assert total == 150, \
                        f"silent short rows: {total}/150 without partialResult"
                    outcomes.append("full")
            snap = get_ledger().snapshot()
            assert snap["totalBytes"] <= capacity, \
                f"query {i}: resident {snap['totalBytes']} > {capacity}"
    fired = sched.fired()
    reset_ledger()
    get_registry().reset()
    clear_download_quarantine()
    return outcomes, fired


def test_tiering_chaos_lane_invariants_and_determinism(tmp_path):
    """Memory pressure x seeded download faults yields ONLY full /
    flagged-partial / typed outcomes with residency bounded by the pinned
    capacity, and two same-seed runs are byte-equal."""
    run_a = _tiering_chaos_scenario(tmp_path / "a", seed=77)
    run_b = _tiering_chaos_scenario(tmp_path / "b", seed=77)
    assert run_a == run_b
    outcomes, fired = run_a
    for o in outcomes:
        assert o in ("full", "partial") or o.startswith("error:"), outcomes
    # non-vacuous: the download faults actually bit the cold reloads, the
    # retry budget absorbed at least one of them into a FULL answer, and at
    # least one query degraded (typed or flagged) instead of lying
    assert fired.get("deepstore.download.fail", 0) > 0, fired
    assert "full" in outcomes, outcomes
    assert any(o != "full" for o in outcomes), outcomes


# -- event journal: seeded chaos determinism + flight recorder ----------------

def _event_chaos_scenario(work_dir, seed, queries=12):
    """The acceptance lane for the event journal: the overload scenario
    (broker pinned SHEDDING, seeded server.slow/server.crash schedule on a
    single-worker scatter pool) followed by a synthetic SLO burn escalation
    (HEALTHY -> DEGRADED -> UNHEALTHY) that must trip the flight recorder
    exactly once. Returns (stable event sequence json, incident count, fire
    counts). The stable sequence keeps per-node causal fields ONLY — (node,
    seq, kind, severity, table, segment), sorted by (node, seq) — because
    tsMs/gseq depend on wall clock and cross-node arrival interleaving."""
    from concurrent.futures import ThreadPoolExecutor

    from pinot_tpu.utils.events import get_journal

    get_journal().clear()
    cluster = QuickCluster(num_servers=2, work_dir=str(work_dir))
    schema = Schema("metrics", [dimension("user", DataType.STRING),
                                metric("value", DataType.DOUBLE)])
    cfg = cluster.create_table(schema, TableConfig("metrics", replication=2))
    for seg in range(2):
        cluster.ingest_columns(cfg, {
            "user": [f"u{seg}_{i}" for i in range(50)],
            "value": [1.0] * 50})
    cluster.broker._pool.shutdown(wait=True)
    cluster.broker._pool = ThreadPoolExecutor(max_workers=1)
    cluster.catalog.put_property("clusterConfig/broker.admission.enabled",
                                 "true")
    cluster.catalog.put_property("clusterConfig/broker.admission.queue.high",
                                 "1")

    sched = FaultSchedule({"server.slow": {"p": 0.4, "latencyMs": 10},
                           "server.crash": {"p": 0.3}}, seed=seed)
    with faults.active(sched):
        for i in range(queries):
            for s in cluster.servers:
                cluster.revive_server(s.instance_id)
                cluster.broker.failure_detector.notify_healthy(s.instance_id)
            sql = ("SELECT user, value FROM metrics LIMIT 20000" if i % 2
                   else "SELECT COUNT(*) FROM metrics")
            try:
                cluster.query(sql)
            except Exception:
                pass   # outcomes are the overload lane's concern; events here

    # deterministic SLO escalation on synthetic counters (test_table_slo's
    # timeline): the UNHEALTHY edge must capture exactly one incident
    c = cluster.controller
    cluster.catalog.put_property("clusterConfig/slo.latency.p99.ms", "100")
    cluster.catalog.put_property("clusterConfig/slo.error.rate", "0.01")
    counters = {"numQueries": 1000, "numErrors": 0, "numOverSlo": 0}
    c.slo_pollers["b1"] = lambda: {"tableStats": {"metrics": dict(counters)}}
    assert c.run_slo_check(now=1000.0) == {"metrics": "HEALTHY"}
    counters.update(numQueries=2000)
    assert c.run_slo_check(now=1060.0) == {"metrics": "HEALTHY"}
    counters.update(numQueries=3000, numErrors=40)
    assert c.run_slo_check(now=1120.0) == {"metrics": "DEGRADED"}
    counters.update(numQueries=4000, numErrors=540)
    assert c.run_slo_check(now=1180.0) == {"metrics": "UNHEALTHY"}
    assert c.run_slo_check(now=1240.0) == {"metrics": "UNHEALTHY"}  # no edge

    rows = get_journal().events_since(0)["events"]
    stable = sorted((e["node"], e["seq"], e["kind"], e["severity"],
                     e.get("table", ""), e.get("segment", ""))
                    for e in rows)
    return json.dumps(stable), c.incidents(), sched.fired()


def test_event_chaos_determinism_and_single_incident(tmp_path):
    """Two same-seed runs of the overload+SLO lane produce byte-equal stable
    event sequences and exactly one incident bundle each."""
    seq_a, incidents_a, fired_a = _event_chaos_scenario(tmp_path / "a",
                                                        seed=4242)
    seq_b, incidents_b, fired_b = _event_chaos_scenario(tmp_path / "b",
                                                        seed=4242)
    assert seq_a == seq_b                      # byte-equal across runs
    assert fired_a == fired_b
    assert len(incidents_a) == 1 and len(incidents_b) == 1
    bundle = incidents_a[0]
    assert bundle["plane"] == "slo" and bundle["key"] == "metrics"
    assert bundle["status"] == "UNHEALTHY"
    # the bundle froze the tripping transition and the broker's view
    assert any(e["kind"] == "verdict.slo" and
               e["attrs"]["toState"] == "UNHEALTHY"
               for e in bundle["events"])
    assert "broker_0" in bundle["snapshots"]["nodes"]
    # non-vacuous: the chaos half actually journaled overload + fault kinds
    kinds = {t[2] for t in json.loads(seq_a)}
    assert "admission.state" in kinds, kinds
    assert "fault.fired" in kinds, kinds
    assert "server.registered" in kinds
    # verdict edges rode the journal: exactly the two SLO transitions plus
    # the incident capture, never one per tick
    slo_edges = [t for t in json.loads(seq_a) if t[2] == "verdict.slo"]
    assert len(slo_edges) == 2, slo_edges
    assert sum(1 for t in json.loads(seq_a)
               if t[2] == "incident.captured") == 1
