"""Completion-protocol chaos tests: committer crashes, controller restarts,
replica divergence.

Reference pattern: `SegmentCompletionIntegrationTest` (scripted FSM races) and
ChaosMonkey scenarios — committer dies before/after commitStart, controller loses
its in-memory FSMs mid-protocol, a laggard replica discards and downloads the
committed copy. Every scenario ends with a differential query check: no data loss.
"""

import json
import time

import pytest

from pinot_tpu.cluster import QuickCluster
from pinot_tpu.cluster.catalog import ONLINE, STATUS_DONE
from pinot_tpu.cluster.completion import (CATCHUP, COMMIT, COMMIT_CONTINUE,
                                          COMMIT_SUCCESS, CompletionFSM, DISCARD,
                                          FAILED, HOLD, KEEP)
from pinot_tpu.ingest.stream import MemoryStream
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.table import StreamConfig, TableConfig, TableType


@pytest.fixture(autouse=True)
def _reset_streams():
    MemoryStream.reset_all()
    yield
    MemoryStream.reset_all()


@pytest.fixture()
def events_schema():
    return Schema("events", [
        dimension("user", DataType.STRING),
        metric("value", DataType.DOUBLE),
    ])


def realtime_cluster(tmp_path, schema, replication=2, flush_rows=20,
                     num_partitions=1):
    cluster = QuickCluster(num_servers=2, work_dir=str(tmp_path))
    cfg = TableConfig("events", table_type=TableType.REALTIME,
                      replication=replication,
                      stream=StreamConfig(stream_type="memory", topic="events_topic",
                                          decoder="json",
                                          flush_threshold_rows=flush_rows))
    cluster.create_realtime_table(schema, cfg, num_partitions)
    return cluster, cfg


def produce(topic, partition, rows):
    stream = MemoryStream.get(topic)
    for row in rows:
        stream.produce(json.dumps(row), partition=partition)


# -- FSM-level crash scripts --------------------------------------------------

def test_committer_crash_before_commit_start():
    """The elected committer dies without ever calling commitStart; after the
    commit timeout a surviving replica is re-elected and commits."""
    fsm = CompletionFSM("seg", num_replicas=2, commit_timeout_s=0.05)
    assert fsm.on_consumed("s1", 50)["status"] == HOLD
    # s2 has the higher offset: elected, told to COMMIT... and then crashes
    assert fsm.on_consumed("s2", 100)["status"] == COMMIT
    time.sleep(0.1)
    # s1 re-reports after the timeout: the silent committer's stale offset is
    # struck so the re-election can land on a live server
    r = fsm.on_consumed("s1", 50)
    assert r["status"] == COMMIT and fsm.committer == "s1"
    assert fsm.on_commit_start("s1") == COMMIT_CONTINUE
    assert fsm.on_commit_end("s1", 50) == COMMIT_SUCCESS
    # the resurrected old committer cannot double-commit; it discards (its 100 >
    # the committed 50 means its local build diverges from the committed copy)
    assert fsm.on_commit_start("s2") == FAILED
    assert fsm.on_consumed("s2", 100)["status"] == DISCARD


def test_committer_crash_mid_commit():
    """Committer crashes AFTER commitStart (deep-store upload may be in flight);
    the COMMITTING state itself times out and another replica takes over."""
    fsm = CompletionFSM("seg", num_replicas=2, commit_timeout_s=0.05)
    fsm.on_consumed("s1", 100)
    r = fsm.on_consumed("s2", 100)   # tie: s2 wins (offset, name) order
    assert fsm.committer == "s2"
    assert fsm.on_commit_start("s2") == COMMIT_CONTINUE   # ...and s2 dies here
    time.sleep(0.1)
    r = fsm.on_consumed("s1", 100)
    assert r["status"] == COMMIT and fsm.committer == "s1"
    assert fsm.on_commit_start("s1") == COMMIT_CONTINUE
    # the zombie's late commitEnd must not be accepted
    assert fsm.on_commit_end("s2", 100) == FAILED
    assert fsm.on_commit_end("s1", 100) == COMMIT_SUCCESS
    # caught-up peer keeps its local build
    assert fsm.on_consumed("s2", 100)["status"] == KEEP


def test_commit_start_adoption_after_controller_restart():
    """Controller restarts between sending COMMIT and receiving commitStart: the
    rebuilt (HOLDING, committer-less) FSM adopts the in-flight committer —
    but ONLY a rebuilt FSM, and only for replica-set members."""
    fsm = CompletionFSM("seg", num_replicas=2, rebuilt=True,
                        replica_set=frozenset({"s1", "s2"}))
    # a server outside the replica set can never hijack the commit
    assert fsm.on_commit_start("rogue") == FAILED
    assert fsm.on_commit_start("s1") == COMMIT_CONTINUE
    assert fsm.committer == "s1"
    # a second replica racing commitStart after the failover loses
    assert fsm.on_commit_start("s2") == FAILED
    assert fsm.on_commit_end("s1", 80) == COMMIT_SUCCESS


def test_no_adoption_on_fresh_fsm():
    """A brand-new segment's FSM (not rebuilt from a restart) still requires a
    real election: commitStart without a prior COMMIT is rejected."""
    fsm = CompletionFSM("seg", num_replicas=2)
    assert fsm.on_commit_start("s1") == FAILED
    assert fsm.committer is None and fsm.state == "HOLDING"


def test_laggard_catchup_script():
    """CATCHUP drives a behind replica to the committer's offset before commit."""
    fsm = CompletionFSM("seg", num_replicas=2)
    fsm.on_consumed("s1", 90)
    r = fsm.on_consumed("s2", 100)
    assert fsm.committer == "s2"
    r = fsm.on_consumed("s1", 90)
    assert r["status"] == CATCHUP and r["offset"] == 100
    r = fsm.on_consumed("s1", 100)   # caught up: parks until the commit lands
    assert r["status"] == HOLD


# -- cluster-level chaos ------------------------------------------------------

def test_controller_restart_between_commit_start_and_end(tmp_path, events_schema):
    """Controller loses its FSMs while the committer is building the segment
    (between commitStart and commitEnd): the rebuilt FSM adopts the in-flight
    committer at commitEnd instead of FAILing it into terminal ERROR."""
    cluster, cfg = realtime_cluster(tmp_path, events_schema, flush_rows=20,
                                    replication=1)
    table = cfg.table_name_with_type
    produce("events_topic", 0, [{"user": f"u{i}", "value": 1.0}
                                for i in range(25)])
    mgr0 = cluster.servers[0].realtime_manager(table)
    mgr1 = cluster.servers[1].realtime_manager(table)
    mgr = mgr0 if mgr0.consumers else mgr1   # replication=1: one server consumes
    mgr.pump_all()
    consumer = next(iter(mgr.consumers.values()))
    orig_build = consumer.build_immutable

    def build_during_restart():
        cluster.controller.llc.fsms.clear()   # the restart happens mid-build
        return orig_build()
    consumer.build_immutable = build_during_restart

    mgr.complete_all()   # single replica: elected immediately -> COMMIT -> build
    done = [m for m in cluster.catalog.segments[table].values()
            if m.status == STATUS_DONE]
    assert len(done) == 1, "commitEnd after FSM loss must adopt, not FAIL"
    assert int(done[0].end_offset) == 25
    assert cluster.query("SELECT COUNT(*) FROM events").rows[0][0] == 25

def test_controller_restart_mid_consumption(tmp_path, events_schema):
    """Losing every in-memory FSM mid-protocol (controller restart) must not
    strand the segment: FSMs rebuild from catalog metadata and the commit
    completes with no data loss."""
    cluster, cfg = realtime_cluster(tmp_path, events_schema, flush_rows=20)
    table = cfg.table_name_with_type
    produce("events_topic", 0, [{"user": f"u{i}", "value": float(i)}
                                for i in range(25)])
    cluster.pump_realtime(table)          # consume; end criteria reached
    before = cluster.query("SELECT COUNT(*) FROM events").rows[0][0]
    assert before == 25

    # "restart": the durable catalog survives, the in-memory FSMs do not
    cluster.controller.llc.fsms.clear()

    for _ in range(4):
        cluster.pump_realtime(table)
    metas = cluster.catalog.segments[table]
    done = [m for m in metas.values() if m.status == STATUS_DONE]
    assert len(done) == 1, "commit must complete after FSM loss"
    assert int(done[0].end_offset) == 25
    # differential: every row still answers
    assert cluster.query("SELECT COUNT(*) FROM events").rows[0][0] == 25


def test_replica_divergence_download_from_deepstore(tmp_path, events_schema):
    """One replica never consumes; after the other commits, the laggard serves
    the committed copy from the deep store — both replicas answer identically."""
    cluster, cfg = realtime_cluster(tmp_path, events_schema, flush_rows=20,
                                    replication=2)
    table = cfg.table_name_with_type
    produce("events_topic", 0, [{"user": f"u{i}", "value": 1.0}
                                for i in range(25)])

    # only server_0 consumes; server_1 is wedged (paused process)
    mgr0 = cluster.servers[0].realtime_manager(table)
    mgr0.pump_all()
    mgr0.complete_all()      # first consumed report -> HOLD (1/2 replicas)
    mgr0.complete_all()      # re-report -> elected -> COMMIT -> committed
    metas = cluster.catalog.segments[table]
    done = [m for m in metas.values() if m.status == STATUS_DONE]
    assert len(done) == 1
    committed = done[0]

    # ideal-state flip drove BOTH replicas ONLINE; the laggard (which had
    # nothing) must have downloaded the committed copy from the deep store
    ev = cluster.catalog.external_view[table]
    assert set(ev[committed.name].values()) == {ONLINE}
    assert committed.name in cluster.servers[1].segments_served(table)

    # differential: each replica alone answers the full committed data
    cluster.kill_server("server_0")
    assert cluster.query("SELECT COUNT(*) FROM events").rows[0][0] == 25
    cluster.revive_server("server_0")
    cluster.kill_server("server_1")
    assert cluster.query("SELECT COUNT(*) FROM events").rows[0][0] == 25


def test_dead_replica_consuming_segment_reassigned(tmp_path, events_schema):
    """Every replica of a CONSUMING segment dies; the validation manager moves
    it to a live server which re-consumes from the durable start offset."""
    cluster, cfg = realtime_cluster(tmp_path, events_schema, flush_rows=100,
                                    replication=1)
    table = cfg.table_name_with_type
    produce("events_topic", 0, [{"user": f"u{i}", "value": 1.0}
                                for i in range(10)])
    cluster.pump_realtime(table)

    # find the server consuming partition 0 and kill it
    seg_name = next(iter(cluster.controller.llc.fsms))
    holder = next(iter(cluster.catalog.ideal_state[table][seg_name]))
    cluster.kill_server(holder)

    # one validation round: segment reassigned to a live server as CONSUMING
    out = cluster.controller.llc.validate()
    assert seg_name in out["reassigned"], out
    new_assignment = cluster.catalog.ideal_state[table][seg_name]
    assert holder not in new_assignment
    assert all(st == "CONSUMING" for st in new_assignment.values())

    # the new replica re-consumes from the start offset: no data loss
    cluster.pump_realtime(table)
    survivor = next(iter(new_assignment))
    node = next(s for s in cluster.servers if s.instance_id == survivor)
    rt = node.realtime_manager(table)
    assert rt is not None and seg_name in rt.consumers
    assert rt.consumers[seg_name].mutable.num_docs == 10
    # a validation round with everyone alive is a no-op
    assert cluster.controller.llc.validate()["reassigned"] == []


def test_committer_crash_cluster_level(tmp_path, events_schema):
    """The elected committer server is killed before it can commit; the second
    replica takes over after the commit timeout and no rows are lost."""
    cluster, cfg = realtime_cluster(tmp_path, events_schema, flush_rows=20,
                                    replication=2)
    table = cfg.table_name_with_type
    produce("events_topic", 0, [{"user": f"u{i}", "value": 1.0}
                                for i in range(25)])
    mgr0 = cluster.servers[0].realtime_manager(table)
    mgr1 = cluster.servers[1].realtime_manager(table)
    mgr0.pump_all()
    mgr1.pump_all()

    # shrink the FSM's commit timeout so the test doesn't wait 120s
    seg_name = next(iter(cluster.controller.llc.fsms))
    fsm = cluster.controller.llc.fsms[seg_name]
    fsm.commit_timeout_s = 0.05

    # server_1 will win the (offset, name) tie-break; script its crash at the
    # exact moment it would commit — it receives COMMIT and then dies
    consumer1 = next(iter(mgr1.consumers.values()))
    consumer1._commit = lambda: None
    mgr0.complete_all()          # first report -> HOLD
    mgr1.complete_all()          # elected -> COMMIT -> "crash"
    assert fsm.committer == "server_1"
    assert not any(m.status == STATUS_DONE
                   for m in cluster.catalog.segments[table].values())
    time.sleep(0.1)

    # the survivor re-reports after the timeout, takes over, commits
    mgr0.complete_all()
    done = [m for m in cluster.catalog.segments[table].values()
            if m.status == STATUS_DONE]
    assert len(done) == 1
    assert int(done[0].end_offset) == 25
    assert fsm.committer == "server_0"
    assert cluster.query("SELECT COUNT(*) FROM events").rows[0][0] == 25
