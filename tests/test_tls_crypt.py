"""TLS on every role endpoint + segment crypter SPI (encryption at rest).

Reference: `pinot-spi/.../crypt/PinotCrypter.java` + TlsIntegrationTest.
"""

import gzip
import os
import subprocess

import numpy as np
import pytest

from pinot_tpu.crypt import (EncryptedFS, XorCrypter, create_crypter,
                             register_crypter, SegmentCrypter)
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.segment.writer import SegmentBuilder
from pinot_tpu.table import TableConfig

from conftest import wait_until


def test_xor_crypter_roundtrip_and_registry():
    c = create_crypter("xor", {"key": "secret"})
    data = os.urandom(4096) + b"tail"
    enc = c.encrypt(data)
    assert enc != data
    assert c.decrypt(enc) == data
    with pytest.raises(KeyError):
        create_crypter("aes-fantasy")

    class Rot1(SegmentCrypter):
        name = "rot1"

        def encrypt(self, d):
            return bytes((b + 1) % 256 for b in d)

        def decrypt(self, d):
            return bytes((b - 1) % 256 for b in d)

    register_crypter(Rot1)  # the SPI seam: third-party crypters plug in
    assert create_crypter("rot1").decrypt(
        create_crypter("rot1").encrypt(b"xyz")) == b"xyz"


def test_encrypted_fs_at_rest_and_cluster_roundtrip(tmp_path):
    """Segments uploaded through EncryptedFS are NOT readable tars at rest,
    yet the full upload -> assign -> load -> query path works unchanged."""
    from pinot_tpu.cluster.catalog import Catalog
    from pinot_tpu.cluster.controller import Controller
    from pinot_tpu.cluster.deepstore import LocalDeepStore
    from pinot_tpu.cluster.broker import Broker
    from pinot_tpu.cluster.server import ServerNode

    fs = EncryptedFS(LocalDeepStore(str(tmp_path / "ds")),
                     XorCrypter({"key": "k1"}))
    catalog = Catalog()
    ctrl = Controller("c0", catalog, fs, str(tmp_path / "c"))
    node = ServerNode("server_0", catalog, fs, str(tmp_path / "s0"))
    broker = Broker("b0", catalog)
    broker.register_server_handle("server_0", node.execute_partial)

    schema = Schema("enc", [dimension("k"), metric("v", DataType.DOUBLE)])
    ctrl.add_schema(schema)
    ctrl.add_table(TableConfig("enc"))
    seg = SegmentBuilder(schema).build(
        {"k": ["a", "b", "a"], "v": np.array([1.0, 2.0, 3.0])},
        str(tmp_path / "b"), "enc_0")
    meta = ctrl.upload_segment("enc_OFFLINE", seg)

    # at rest: the deep-store blob is PCRY-framed ciphertext, not a gzip
    blob = open(os.path.join(str(tmp_path / "ds"),
                             meta.download_path), "rb").read()
    assert blob.startswith(b"PCRY")
    with pytest.raises(gzip.BadGzipFile):
        gzip.decompress(blob)

    # the server (same crypter) loads and serves it
    assert wait_until(lambda: broker.handle_query(
        "SELECT COUNT(*), SUM(v) FROM enc").rows[0] == [3, 6.0], timeout=20)

    # a process with the WRONG crypter fails loudly, never untars garbage
    bad = EncryptedFS(LocalDeepStore(str(tmp_path / "ds")),
                      XorCrypter({"key": "k1"}))
    bad.crypter.name = "other"
    with pytest.raises(ValueError, match="encrypted with"):
        bad.download(meta.download_path, str(tmp_path / "out.tar.gz"))


@pytest.fixture(scope="module")
def tls_material(tmp_path_factory):
    d = tmp_path_factory.mktemp("tls")
    cert, key = str(d / "cert.pem"), str(d / "key.pem")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-keyout", key,
         "-out", cert, "-days", "1", "-nodes", "-subj", "/CN=127.0.0.1",
         "-addext", "subjectAltName=IP:127.0.0.1"],
        check=True, capture_output=True)
    return cert, key


def test_process_cluster_with_tls(tmp_path, tls_material):
    """Every role process serves HTTPS; inter-role traffic (catalog watch,
    completion, scatter) and the external client verify against the
    self-signed CA — a full create/upload/query flow under TLS."""
    from pinot_tpu.cluster.http_service import set_default_tls
    from pinot_tpu.cluster.process import ProcessCluster
    cert, key = tls_material
    cfg_path = str(tmp_path / "tls.properties")
    with open(cfg_path, "w") as f:
        f.write(f"tls.enabled=true\ntls.cert={cert}\ntls.key={key}\n"
                f"tls.ca={cert}\n")
    set_default_tls(cafile=cert)  # this test process is the external client
    try:
        with ProcessCluster(num_servers=2, work_dir=str(tmp_path),
                            config_path=cfg_path) as cluster:
            assert cluster.controller_url.startswith("https://")
            assert cluster.broker_url.startswith("https://")
            schema = Schema("sec", [dimension("k"),
                                    metric("v", DataType.DOUBLE)])
            cluster.controller.add_schema(schema)
            cluster.controller.add_table(TableConfig("sec"))
            seg = SegmentBuilder(schema).build(
                {"k": ["x", "y"], "v": np.array([5.0, 7.0])},
                str(tmp_path / "b"), "sec_0")
            cluster.controller.upload_segment("sec_OFFLINE", seg)
            assert wait_until(lambda: cluster.query(
                "SELECT SUM(v) FROM sec")["resultTable"]["rows"][0][0] == 12.0,
                timeout=30)
            # plaintext client is REFUSED by the TLS listener
            import urllib.request
            import urllib.error
            plain = cluster.controller_url.replace("https://", "http://")
            with pytest.raises(Exception):
                urllib.request.urlopen(f"{plain}/health", timeout=5)
    finally:
        set_default_tls(None)
