"""Event journal + controller timeline + incident flight recorder tests.

Covers the journal's core contracts (per-node monotonic seq exact under
8-thread concurrency, strict oldest-first ring eviction with conservation,
closed kind schema), the controller's cursor-incremental timeline merge
across multiple journal sources, the edge-triggered verdict planes (one
event per transition, never per tick), the flight recorder's
exactly-one-bundle-per-episode behavior, the HTTP debug routes, and the
operator tools that render all of it.
"""

import json
import threading

import pytest

from pinot_tpu.cluster.catalog import Catalog
from pinot_tpu.cluster.controller import Controller
from pinot_tpu.cluster.deepstore import LocalDeepStore
from pinot_tpu.ingest.stream import MemoryStream
from pinot_tpu.utils import faults
from pinot_tpu.utils.events import (EventJournal, KINDS, SEVERITIES,
                                    get_journal)


@pytest.fixture(autouse=True)
def _clean_journal():
    """The process journal is global (all in-proc roles share it): every test
    starts and ends with an empty ring and the default node/capacity."""
    j = get_journal()
    j.clear()
    j.configure(node="proc", capacity=512)
    faults.deactivate()
    MemoryStream.reset_all()
    yield
    faults.deactivate()
    MemoryStream.reset_all()
    j.clear()
    j.configure(node="proc", capacity=512)


def controller(tmp_path, name="c0"):
    return Controller(name, Catalog(), LocalDeepStore(str(tmp_path / "ds")),
                      str(tmp_path / name))


# -- journal core -------------------------------------------------------------

def test_journal_emit_schema_and_seqs():
    j = EventJournal(capacity=32, node="n0")
    ev1 = j.emit("segment.online", table="t_REALTIME", segment="s1")
    ev2 = j.emit("server.down", node="n1", server="s0")
    ev3 = j.emit("segment.committed", table="t_REALTIME")
    # per-node seq is monotonic per node; gseq is journal arrival order
    assert (ev1.seq, ev2.seq, ev3.seq) == (1, 1, 2)
    assert [ev1.gseq, ev2.gseq, ev3.gseq] == [1, 2, 3]
    d = ev1.as_dict()
    assert d["node"] == "n0" and d["kind"] == "segment.online"
    assert d["severity"] == "INFO" and d["table"] == "t_REALTIME"
    assert "traceId" not in d and "attrs" not in d   # empty fields omitted
    assert ev2.as_dict()["severity"] == "ERROR"      # schema default
    assert ev2.as_dict()["attrs"] == {"server": "s0"}
    # severity override (direction-dependent sites)
    assert j.emit("admission.state", severity="INFO").severity == "INFO"


def test_journal_rejects_unregistered_kind():
    j = EventJournal()
    with pytest.raises(ValueError, match="unregistered event kind"):
        j.emit("segment.mystery")
    assert len(j) == 0 and j.emitted == 0


def test_kinds_schema_table_is_well_formed():
    for kind, (severity, description) in KINDS.items():
        assert severity in SEVERITIES, kind
        assert description, kind


def test_ring_eviction_oldest_first_and_conservation():
    j = EventJournal(capacity=4, node="n0")
    for i in range(10):
        j.emit("bench.probe", i=i)
    snap = j.snapshot()
    assert snap["emitted"] == 10 and snap["retained"] == 4
    assert snap["evicted"] == 6
    assert snap["emitted"] == snap["retained"] + snap["evicted"]
    # survivors are exactly the newest window, newest first
    assert [e["attrs"]["i"] for e in j.entries()] == [9, 8, 7, 6]
    # configure() shrink trims oldest-first and keeps the conservation law
    j.configure(capacity=2)
    snap = j.snapshot()
    assert snap["retained"] == 2 and snap["evicted"] == 8
    assert [e["attrs"]["i"] for e in j.entries()] == [9, 8]


def test_events_since_cursor_is_incremental():
    j = EventJournal(capacity=32, node="n0")
    j.emit("bench.probe", i=0)
    j.emit("bench.probe", i=1)
    first = j.events_since(0)
    assert [e["attrs"]["i"] for e in first["events"]] == [0, 1]
    j.emit("bench.probe", i=2)
    second = j.events_since(first["cursor"])
    assert [e["attrs"]["i"] for e in second["events"]] == [2]
    assert j.events_since(second["cursor"])["events"] == []


def test_emit_seq_exact_under_8_threads():
    j = EventJournal(capacity=10_000)
    per_thread = 100

    def worker(tid):
        for _ in range(per_thread):
            j.emit("bench.probe", node=f"n{tid}")   # own node stream
            j.emit("bench.probe", node="shared")    # contended node stream
    threads = [threading.Thread(target=worker, args=(t,)) for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    rows = j.events_since(0)["events"]
    assert j.emitted == 8 * per_thread * 2
    by_node = {}
    for e in rows:
        by_node.setdefault(e["node"], []).append(e["seq"])
    # per-node seqs are exactly 1..N — no gaps, no duplicates, even on the
    # node all 8 threads contend on
    assert sorted(by_node["shared"]) == list(range(1, 8 * per_thread + 1))
    for tid in range(8):
        assert sorted(by_node[f"n{tid}"]) == list(range(1, per_thread + 1))
    # gseq is a strict arrival order over the whole journal
    gseqs = [e["gseq"] for e in rows]
    assert gseqs == sorted(gseqs) and len(set(gseqs)) == len(gseqs)


# -- controller timeline merge ------------------------------------------------

def test_timeline_merge_two_sources_no_duplication(tmp_path):
    ctrl = controller(tmp_path)
    j1 = EventJournal(node="server_1")
    j2 = EventJournal(node="server_2")
    ctrl.event_pollers["server_1"] = j1.events_since
    ctrl.event_pollers["server_2"] = j2.events_since
    j1.emit("segment.online", table="t", segment="a")
    j2.emit("server.down", server="x")
    assert ctrl.run_event_check() == 2
    # second tick with no new events merges nothing (cursors advanced)
    assert ctrl.run_event_check() == 0
    j2.emit("server.up", server="x")
    assert ctrl.run_event_check() == 1
    rows = ctrl.timeline()
    assert [r["kind"] for r in rows if r["node"].startswith("server_")] == \
        ["segment.online", "server.down", "server.up"]
    summary = ctrl.events_summary()
    assert summary["cursors"]["server_1"] == 1
    assert summary["cursors"]["server_2"] == 2
    assert summary["unreachable"] == []


def test_timeline_filters_and_unreachable(tmp_path):
    ctrl = controller(tmp_path)
    j = EventJournal(node="s1")
    ctrl.event_pollers["s1"] = j.events_since

    def dead(_since):
        raise ConnectionError("down")
    ctrl.event_pollers["s9"] = dead
    j.emit("segment.online", table="t1", segment="a")
    j.emit("tier.evicted", table="t2", segment="b")
    j.emit("server.down", server="x")
    ctrl.run_event_check()
    assert [r["kind"] for r in ctrl.timeline(kind="server.down")] == \
        ["server.down"]
    assert [r["segment"] for r in ctrl.timeline(table="t2")] == ["b"]
    # severity floor admits the level and everything worse
    assert {r["severity"] for r in ctrl.timeline(severity="WARN")} == \
        {"ERROR"}
    assert len(ctrl.timeline(limit=1)) == 1
    assert ctrl.events_summary()["unreachable"] == ["s9"]
    # an unreachable source's cursor is untouched: once it heals, the next
    # tick re-pulls from the same spot
    ctrl.event_pollers["s9"] = EventJournal(node="s9").events_since
    ctrl.run_event_check()
    assert ctrl.events_summary()["unreachable"] == []


# -- verdict edges + flight recorder ------------------------------------------

def test_verdict_edge_triggered_exactly_once(tmp_path):
    ctrl = controller(tmp_path)
    j = get_journal()
    ctrl._note_verdict("slo", "t1", "DEGRADED", ["burn 2x"])
    ctrl._note_verdict("slo", "t1", "DEGRADED", ["burn 2x"])   # no edge
    ctrl._note_verdict("slo", "t1", "DEGRADED", ["burn 3x"])   # still no edge
    edges = [e for e in j.entries() if e["kind"] == "verdict.slo"]
    assert len(edges) == 1
    assert edges[0]["attrs"]["fromState"] == "HEALTHY"
    assert edges[0]["attrs"]["toState"] == "DEGRADED"
    assert edges[0]["severity"] == "WARN"
    # DEGRADED does not trip the recorder by default
    assert ctrl.incidents() == []
    # recovery is an edge too, at INFO
    ctrl._note_verdict("slo", "t1", "HEALTHY", [])
    edges = [e for e in j.entries() if e["kind"] == "verdict.slo"]
    assert len(edges) == 2 and edges[0]["severity"] == "INFO"
    # pruning forgets the key: the next DEGRADED is a fresh edge
    ctrl._prune_verdicts("slo", set())
    ctrl._note_verdict("slo", "t1", "DEGRADED", [])
    assert len([e for e in j.entries() if e["kind"] == "verdict.slo"]) == 3


def test_incident_captured_once_per_episode(tmp_path):
    ctrl = controller(tmp_path)
    ctrl._note_verdict("ingestion", "t1", "UNHEALTHY", ["stalled"])
    ctrl._note_verdict("ingestion", "t1", "UNHEALTHY", ["stalled"])  # no-op
    assert len(ctrl.incidents()) == 1
    b = ctrl.incidents()[0]
    assert b["plane"] == "ingestion" and b["key"] == "t1"
    assert b["status"] == "UNHEALTHY" and b["reasons"] == ["stalled"]
    for field in ("id", "tsMs", "events", "snapshots", "slowTraceIds"):
        assert field in b
    for snap_key in ("ingestionStatus", "sloStatus", "memoryStatus",
                     "workloadStatus", "nodes"):
        assert snap_key in b["snapshots"]
    # the bundle's timeline includes the tripping transition itself
    assert any(e["kind"] == "verdict.ingestion" for e in b["events"])
    # recovery then relapse captures a SECOND bundle (new episode)
    ctrl._note_verdict("ingestion", "t1", "HEALTHY", [])
    ctrl._note_verdict("ingestion", "t1", "UNHEALTHY", ["stalled again"])
    assert [i["id"] for i in ctrl.incidents()] == [2, 1]   # newest first
    # the capture itself is journaled
    assert any(e["kind"] == "incident.captured" for e in get_journal().entries())


def test_incident_on_degraded_knob_and_ring_cap(tmp_path):
    ctrl = controller(tmp_path)
    ctrl.catalog.put_property(
        "clusterConfig/controller.incident.on.degraded", "true")
    ctrl.catalog.put_property("clusterConfig/controller.incident.ring.size",
                              "2")
    ctrl._note_verdict("memory", "t1", "DEGRADED", ["headroom low"])
    assert len(ctrl.incidents()) == 1
    for n in range(2, 5):   # flap to force captures past the ring cap
        ctrl._note_verdict("memory", "t1", "HEALTHY", [])
        ctrl._note_verdict("memory", "t1", "DEGRADED", [f"flap {n}"])
    assert [i["id"] for i in ctrl.incidents()] == [4, 3]   # oldest evicted


def test_incident_poller_snapshot_and_slow_traces(tmp_path):
    ctrl = controller(tmp_path)
    ctrl.incident_pollers["broker_0"] = lambda: {
        "admission": {"state": "SHEDDING"},
        "recentSlowQueries": [{"stats": {"traceId": "tr-1"}},
                              {"stats": {"traceId": "tr-1"}}]}

    def dead():
        raise ConnectionError("down")
    ctrl.incident_pollers["broker_1"] = dead
    b = ctrl._capture_incident("slo", "t1", "UNHEALTHY", ["burn"])
    assert b["snapshots"]["nodes"]["broker_0"]["admission"]["state"] == \
        "SHEDDING"
    assert b["snapshots"]["nodes"]["broker_1"] == {"unreachable": True}
    assert b["slowTraceIds"] == ["tr-1"]   # deduped


# -- end-to-end lifecycle timeline --------------------------------------------

def test_quickcluster_lifecycle_causal_timeline(tmp_path):
    """The acceptance arc without chaos: consuming -> commit -> ONLINE ->
    cold demote -> lazy reload, every transition on the merged timeline in
    causal order."""
    from pinot_tpu.cluster import QuickCluster
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.table import StreamConfig, TableConfig, TableType

    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    schema = Schema("events", [dimension("user", DataType.STRING),
                               metric("value", DataType.DOUBLE)])
    cfg = TableConfig("events", table_type=TableType.REALTIME, replication=1,
                      stream=StreamConfig(stream_type="memory",
                                          topic="events_topic", decoder="json",
                                          flush_threshold_rows=5))
    cluster.create_realtime_table(schema, cfg, 1)
    stream = MemoryStream.get("events_topic")
    for i in range(10):
        stream.produce(json.dumps({"user": f"u{i}", "value": 1.0}),
                       partition=0)
    cluster.pump_realtime("events_REALTIME")
    committed = [s for s, m in
                 cluster.catalog.segments["events_REALTIME"].items()
                 if m.status == "DONE"]
    assert committed
    assert cluster.controller.demote_segment_to_cold("events_REALTIME",
                                                     committed[0])
    assert cluster.query("SELECT COUNT(*) FROM events").rows == [[10]]
    cluster.controller.run_event_check()
    kinds = [e["kind"] for e in cluster.controller.timeline()]
    for expected in ("segment.consuming.created", "segment.committed",
                     "segment.online", "segment.cold.demoted",
                     "segment.cold.loaded", "tier.promoted"):
        assert expected in kinds, expected
    # causal order within the lifecycle
    assert kinds.index("segment.committed") < kinds.index("segment.online")
    assert kinds.index("segment.online") < \
        kinds.index("segment.cold.demoted")
    assert kinds.index("segment.cold.demoted") < \
        kinds.index("segment.cold.loaded")
    # cluster_top renders the recent-events panel off this timeline
    from pinot_tpu.tools import cluster_top
    snap = {"tables": {}, "timeline": cluster.controller.timeline(limit=8),
            "eventsSummary": cluster.controller.events_summary()}
    text = cluster_top.render(snap)
    assert "recent events" in text and "segment.cold.demoted" in text


# -- HTTP routes --------------------------------------------------------------

def test_http_event_routes(tmp_path):
    from pinot_tpu.cluster.http_service import HttpError, get_json
    from pinot_tpu.cluster.services import ControllerService

    ctrl = controller(tmp_path)
    svc = ControllerService(ctrl)
    try:
        get_journal().emit("segment.online", node="c0", table="t",
                           segment="s1")
        body = get_json(f"{svc.url}/debug/events?since=0")
        assert [e["kind"] for e in body["events"]] == ["segment.online"]
        assert get_json(
            f"{svc.url}/debug/events?since={body['cursor']}")["events"] == []
        ctrl.run_event_check()
        tl = get_json(f"{svc.url}/debug/timeline?kind=segment.online")
        assert tl["count"] == 1 and tl["events"][0]["segment"] == "s1"
        assert get_json(
            f"{svc.url}/debug/timeline?severity=ERROR")["count"] == 0
        # incidents: empty ring, then one capture, then by-id + 404
        assert get_json(f"{svc.url}/debug/incidents")["incidents"] == []
        ctrl._note_verdict("slo", "t", "UNHEALTHY", ["burn"])
        listing = get_json(f"{svc.url}/debug/incidents")
        assert listing["count"] == 1
        one = get_json(f"{svc.url}/debug/incidents?id=1")
        assert one["plane"] == "slo" and one["key"] == "t"
        with pytest.raises(HttpError):
            get_json(f"{svc.url}/debug/incidents?id=99")
        # /debug rollup carries the light summary
        assert get_json(f"{svc.url}/debug")["events"]["timelineEvents"] >= 1
    finally:
        svc.stop()


# -- operator tools -----------------------------------------------------------

def test_incident_report_renders_bundle(tmp_path, capsys):
    from pinot_tpu.tools.incident_report import main as report_main

    ctrl = controller(tmp_path)
    get_journal().emit("server.down", node="broker_0", server="server_1")
    ctrl.incident_pollers["broker_0"] = lambda: {
        "recentSlowQueries": [{"stats": {"traceId": "tr-9"}}]}
    ctrl._slo_status["t1"] = {"table": "t1", "verdict": "UNHEALTHY",
                              "reasons": ["availability burn 5.0x"]}
    ctrl._note_verdict("slo", "t1", "UNHEALTHY", ["availability burn 5.0x"])
    path = tmp_path / "incidents.json"
    path.write_text(json.dumps({"incidents": ctrl.incidents()}))
    assert report_main(["incident_report", str(path)]) == 0
    out = capsys.readouterr().out
    assert "incident #1" in out and "plane=slo" in out
    assert "reason: availability burn 5.0x" in out
    assert "server.down" in out and "verdict.slo" in out
    assert "tr-9" in out
    # --id selects one bundle; unknown ids answer visibly
    assert report_main(["incident_report", "--id", "1", str(path)]) == 0
    assert "UNHEALTHY" in capsys.readouterr().out
    assert report_main(["incident_report", "--id", "7", str(path)]) == 0
    assert "unknown incident id 7" in capsys.readouterr().out


def test_query_report_interleaves_journal_events(capsys):
    from pinot_tpu.tools.query_report import main as report_main
    doc = {
        "traces": [{"traceId": "tr-1", "sql": "SELECT 1",
                    "timeUsedMs": 12.0,
                    "spans": [{"name": "broker.query", "startMs": 0.0,
                               "durationMs": 12.0, "depth": 0}]}],
        "events": [
            {"tsMs": 1000, "seq": 1, "node": "server_0",
             "kind": "server.down", "severity": "ERROR", "traceId": "tr-1"},
            {"tsMs": 2000, "seq": 2, "node": "broker_0",
             "kind": "hedge.suppressed", "severity": "WARN", "table": "t",
             "traceId": "tr-1"},
            {"tsMs": 1500, "seq": 3, "node": "broker_0",
             "kind": "backpressure.hold", "severity": "WARN",
             "traceId": "tr-OTHER"}],
    }
    import io
    import sys as _sys
    _sys.stdin = io.StringIO(json.dumps(doc))
    try:
        assert report_main(["query_report"]) == 0
    finally:
        _sys.stdin = _sys.__stdin__
    out = capsys.readouterr().out
    assert "journal events (same traceId)" in out
    assert "server.down" in out and "hedge.suppressed" in out
    assert "backpressure.hold" not in out   # other trace's event filtered
    # chronological: the earlier event renders first
    assert out.index("server.down") < out.index("hedge.suppressed")


def test_cluster_top_events_panel_absent_without_timeline():
    from pinot_tpu.tools import cluster_top
    assert "recent events" not in cluster_top.render({"tables": {}})
