"""Multi-value column tests: writer/reader CSR layout, device+host predicate
parity, MV aggregations, MV group-by explode, transforms, mutable MV, inverted.

Reference patterns: MVScanDocIdIterator semantics ("row matches if ANY value
matches"), CountMV/SumMV/... aggregation functions, MV group key explosion.
"""

import numpy as np
import pytest

from pinot_tpu.query.executor import ServerQueryExecutor, execute_query
from pinot_tpu.schema import DataType, FieldSpec, FieldRole, Schema, dimension, metric
from pinot_tpu.segment.mutable import MutableSegment
from pinot_tpu.segment.reader import load_segment
from pinot_tpu.segment.writer import SegmentBuilder, SegmentGeneratorConfig

SCHEMA = Schema("docs", [
    dimension("doc", DataType.STRING),
    FieldSpec("tags", DataType.STRING, FieldRole.DIMENSION, single_value=False),
    FieldSpec("scores", DataType.INT, FieldRole.DIMENSION, single_value=False),
    metric("weight", DataType.DOUBLE),
])

ROWS = {
    "doc": ["a", "b", "c", "d"],
    "tags": [["x", "y"], ["y"], ["z", "x", "w"], None],
    "scores": [[1, 2], [2, 3], [5], [7, 8]],
    "weight": np.array([1.0, 2.0, 3.0, 4.0]),
}


@pytest.fixture(scope="module")
def seg(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("mv")
    builder = SegmentBuilder(SCHEMA, SegmentGeneratorConfig(
        inverted_index_columns=["tags"]))
    return load_segment(builder.build(dict(ROWS), str(tmp), "docs_0"))


# -- storage roundtrip --------------------------------------------------------

def test_mv_roundtrip(seg):
    r = seg.column("tags")
    assert r.is_multi_value
    vals = r.values()
    assert list(vals[0]) == ["x", "y"]
    assert list(vals[2]) == ["z", "x", "w"]
    assert list(vals[3]) == ["null"]   # None row -> [default null]
    assert r.null_bitmap is not None and r.null_bitmap[3]
    assert r.max_num_values == 3
    scores = seg.column("scores").values()
    assert list(scores[1]) == [2, 3]


def test_mv_inverted_index_postings(seg):
    inv = seg.column("tags").inverted_index
    d = seg.column("tags").dictionary
    docs_with_x = inv.doc_ids_for(d.index_of("x"))
    assert sorted(docs_with_x.tolist()) == [0, 2]


# -- predicate semantics: any value matches ----------------------------------

@pytest.mark.parametrize("use_device", [True, False])
def test_mv_filters(seg, use_device):
    ex = ServerQueryExecutor(use_device=use_device)
    res = ex.execute([seg], "SELECT COUNT(*) FROM docs WHERE tags = 'x'")
    assert res.rows[0][0] == 2           # rows a and c contain 'x'
    res = ex.execute([seg], "SELECT COUNT(*) FROM docs WHERE tags IN ('y', 'w')")
    assert res.rows[0][0] == 3           # a, b (y) and c (w)
    res = ex.execute([seg], "SELECT COUNT(*) FROM docs WHERE scores BETWEEN 3 AND 6")
    assert res.rows[0][0] == 2           # b (3) and c (5)
    res = ex.execute([seg], "SELECT COUNT(*) FROM docs WHERE NOT tags = 'y'")
    assert res.rows[0][0] == 2           # c and d have no 'y' at all
    res = ex.execute([seg], "SELECT SUM(weight) FROM docs WHERE tags = 'x'")
    assert res.rows[0][0] == pytest.approx(4.0)


def test_mv_device_host_parity(seg):
    for sql in ["SELECT COUNT(*) FROM docs WHERE tags = 'x'",
                "SELECT COUNT(*) FROM docs WHERE scores >= 2 AND tags IN ('y','z')",
                "SELECT SUM(weight), COUNT(*) FROM docs WHERE scores < 3"]:
        dev = ServerQueryExecutor(use_device=True).execute([seg], sql)
        host = ServerQueryExecutor(use_device=False).execute([seg], sql)
        assert dev.rows == host.rows, sql


# -- MV aggregations ----------------------------------------------------------

def test_mv_aggregations(seg):
    res = execute_query(
        [seg], "SELECT COUNTMV(scores), SUMMV(scores), MINMV(scores), "
               "MAXMV(scores), AVGMV(scores), DISTINCTCOUNTMV(tags) FROM docs")
    row = res.rows[0]
    assert row[0] == 7                       # 2+2+1+2 values
    assert row[1] == pytest.approx(28.0)     # 1+2+2+3+5+7+8
    assert row[2] == 1 and row[3] == 8
    assert row[4] == pytest.approx(28.0 / 7)
    assert row[5] == 5                       # x y z w null


def test_mv_agg_with_filter(seg):
    res = execute_query(
        [seg], "SELECT COUNTMV(tags) FROM docs WHERE weight < 2.5")
    assert res.rows[0][0] == 3               # a: [x,y], b: [y]


# -- MV group-by explode ------------------------------------------------------

def test_mv_group_by_explodes(seg):
    res = execute_query(
        [seg], "SELECT tags, COUNT(*), SUM(weight) FROM docs "
               "GROUP BY tags ORDER BY tags LIMIT 20")
    got = {r[0]: (r[1], r[2]) for r in res.rows}
    assert got["x"] == (2, pytest.approx(4.0))    # docs a, c
    assert got["y"] == (2, pytest.approx(3.0))    # docs a, b
    assert got["z"] == (1, pytest.approx(3.0))
    assert got["w"] == (1, pytest.approx(3.0))
    assert got["null"] == (1, pytest.approx(4.0))  # doc d's default-null row


def test_mv_group_by_with_sv_key(seg):
    res = execute_query(
        [seg], "SELECT doc, tags, COUNT(*) FROM docs "
               "WHERE doc IN ('a', 'b') GROUP BY doc, tags LIMIT 20")
    keys = {(r[0], r[1]) for r in res.rows}
    assert keys == {("a", "x"), ("a", "y"), ("b", "y")}


def test_mv_distinct(seg):
    res = execute_query([seg], "SELECT DISTINCT tags FROM docs LIMIT 20")
    assert {r[0] for r in res.rows} == {"x", "y", "z", "w", "null"}


# -- transforms ---------------------------------------------------------------

def test_arraylength_and_selection(seg):
    res = execute_query(
        [seg], "SELECT doc, ARRAYLENGTH(tags) FROM docs ORDER BY doc LIMIT 10")
    assert [r[1] for r in res.rows] == [2, 1, 3, 1]
    # MV cells in selection results surface as python lists
    res = execute_query([seg], "SELECT doc, tags FROM docs ORDER BY doc LIMIT 10")
    assert res.rows[0][1] == ["x", "y"]


def test_arraylength_filter(seg):
    res = execute_query(
        [seg], "SELECT COUNT(*) FROM docs WHERE ARRAYLENGTH(tags) >= 2")
    assert res.rows[0][0] == 2


def test_arrayelementat(seg):
    res = execute_query(
        [seg], "SELECT doc, ARRAYELEMENTAT(scores, 2) FROM docs ORDER BY doc LIMIT 10")
    assert [r[1] for r in res.rows] == [2, 3, None, 8]


def test_valuein_group_by_explodes(seg):
    res = execute_query(
        [seg], "SELECT VALUEIN(tags, 'x', 'y'), COUNTMV(tags) FROM docs "
               "GROUP BY VALUEIN(tags, 'x', 'y') LIMIT 20")
    got = {r[0]: r[1] for r in res.rows}
    # rows with neither x nor y contribute no group (empty VALUEIN row)
    assert got == {"x": 5, "y": 3}   # x: docs a(2)+c(3) values; y: a(2)+b(1)


def test_sv_agg_over_mv_rejected(seg):
    from pinot_tpu.query.context import QueryValidationError
    with pytest.raises(QueryValidationError, match="SUMMV"):
        execute_query([seg], "SELECT SUM(scores) FROM docs")
    with pytest.raises(QueryValidationError, match="multi-value"):
        execute_query([seg], "SELECT doc FROM docs ORDER BY tags LIMIT 5")


def test_mv_inverted_dedupes_repeated_values(tmp_path):
    builder = SegmentBuilder(SCHEMA, SegmentGeneratorConfig(
        inverted_index_columns=["tags"]))
    seg = load_segment(builder.build(
        {"doc": ["a"], "tags": [["x", "x", "y"]], "scores": [[1]],
         "weight": np.array([1.0])}, str(tmp_path), "dup_0"))
    inv = seg.column("tags").inverted_index
    d = seg.column("tags").dictionary
    # a row repeating a value posts its doc ONCE (reference bitmap semantics)
    assert inv.doc_ids_for(d.index_of("x")).tolist() == [0]


# -- mutable MV ---------------------------------------------------------------

def test_mutable_mv_index_and_query():
    seg = MutableSegment("docs__0__0__1", SCHEMA)
    seg.index({"doc": "a", "tags": ["x", "y"], "scores": [1], "weight": 1.0})
    seg.index({"doc": "b", "tags": ["y"], "scores": [2, 3], "weight": 2.0})
    seg.index({"doc": "c", "tags": None, "scores": [], "weight": 3.0})
    r = seg.column("tags")
    assert r.is_multi_value and r.has_dictionary
    assert list(r.values()[0]) == ["x", "y"]
    assert list(r.values()[2]) == ["null"]
    # empty MV row stores the type's default null (reference MV null handling)
    assert list(seg.column("scores").values()[2]) == [DataType.INT.default_null]

    ex = ServerQueryExecutor(use_device=False)
    res = ex.execute([seg], "SELECT COUNT(*) FROM docs WHERE tags = 'y'", SCHEMA)
    assert res.rows[0][0] == 2
    res = ex.execute([seg], "SELECT SUMMV(scores) FROM docs WHERE weight < 2.5",
                     SCHEMA)
    assert res.rows[0][0] == pytest.approx(6.0)
    res = ex.execute([seg], "SELECT tags, COUNT(*) FROM docs GROUP BY tags LIMIT 10",
                     SCHEMA)
    got = {r[0]: r[1] for r in res.rows}
    assert got == {"x": 1, "y": 2, "null": 1}


def test_mutable_mv_commit_roundtrip(tmp_path):
    """Mutable MV rows survive conversion to an immutable segment."""
    mseg = MutableSegment("docs__0__0__2", SCHEMA)
    mseg.index({"doc": "a", "tags": ["p", "q"], "scores": [1, 2], "weight": 1.0})
    mseg.index({"doc": "b", "tags": ["q"], "scores": [3], "weight": 2.0})
    cols = mseg.snapshot_columns()
    builder = SegmentBuilder(SCHEMA, SegmentGeneratorConfig())
    seg = load_segment(builder.build(cols, str(tmp_path), "docs_imm"))
    assert list(seg.column("tags").values()[0]) == ["p", "q"]
    res = execute_query([seg], "SELECT COUNT(*) FROM docs WHERE tags = 'q'")
    assert res.rows[0][0] == 2


# -- MV percentile / HLL variants (reference: PercentileMV / DistinctCountHLLMV) --

def test_percentile_mv(seg):
    # scores flattened: [1,2,2,3,5,7,8] -> median 3
    res = execute_query([seg], "SELECT PERCENTILEMV(scores, 50) FROM docs")
    flat = np.array([1, 2, 2, 3, 5, 7, 8], dtype=float)
    assert res.rows[0][0] == pytest.approx(float(np.percentile(flat, 50)))
    res2 = execute_query([seg], "SELECT PERCENTILE50MV(scores) FROM docs")
    assert res2.rows[0][0] == res.rows[0][0]


def test_percentile_est_and_tdigest_mv(seg):
    flat = np.array([1, 2, 2, 3, 5, 7, 8], dtype=float)
    for fn in ("PERCENTILEESTMV", "PERCENTILETDIGESTMV"):
        res = execute_query([seg], f"SELECT {fn}(scores, 90) FROM docs")
        assert res.rows[0][0] == pytest.approx(float(np.percentile(flat, 90)),
                                               rel=0.15)


def test_distinctcount_hll_mv(seg):
    res = execute_query([seg], "SELECT DISTINCTCOUNTHLLMV(tags) FROM docs")
    # distinct flattened tags: x y z w + the default 'null' fill = 5
    assert abs(res.rows[0][0] - 5) <= 1


def test_percentile_mv_group_by(seg):
    res = execute_query([seg], "SELECT doc, PERCENTILEMV(scores, 100) FROM docs "
                               "GROUP BY doc ORDER BY doc LIMIT 10")
    assert [r[1] for r in res.rows] == [2.0, 3.0, 5.0, 8.0]


def test_minmaxrange_and_bitmap_mv(seg):
    res = execute_query([seg], "SELECT MINMAXRANGEMV(scores), "
                               "DISTINCTCOUNTBITMAPMV(scores) FROM docs")
    # flattened scores: [1,2,2,3,5,7,8] -> range 7, distinct 6
    assert res.rows[0][0] == 7.0
    assert res.rows[0][1] == 6
