"""Thrift input format: TBinaryProtocol golden vectors, IDL parsing,
round-trips, unknown-field evolution, and end-to-end ingestion.

Mirrors the reference's thrift plugin coverage
(`pinot-plugins/pinot-input-format/pinot-thrift/src/test/...`). Golden bytes
are hand-assembled from the public TBinaryProtocol spec."""

import io
import json

import numpy as np
import pytest

from pinot_tpu.ingest.thriftfmt import (ThriftError, ThriftIDL,
                                        ThriftRecordReader, _Reader,
                                        decode_struct, encode_struct,
                                        make_thrift_decoder, write_structs)

IDL = """
// test schema
enum Color { BLUE = 0, RED = 2, GREEN }

typedef i64 Timestamp

struct Inner {
  1: required string label;
  2: optional double weight;
}

struct Event {
  1: required string user;
  2: optional i64 clicks;
  3: optional double cost;
  4: optional bool active;
  5: optional list<i32> codes;
  6: optional map<string, double> props;
  7: optional Inner inner;
  8: optional Color color;
  9: optional Timestamp ts;
  10: optional binary blob;
  11: optional set<string> tags;
}
"""

ROW = {
    "user": "alice", "clicks": -42, "cost": 3.75, "active": True,
    "codes": [1, -2, 300], "props": {"a": 1.5}, "inner": {"label": "x",
                                                          "weight": 0.5},
    "color": 2, "ts": 1700000000000, "blob": b"\x00\xff",
    "tags": ["t1", "t2"],
}


@pytest.fixture(scope="module")
def idl():
    return ThriftIDL(IDL)


def test_idl_parsing(idl):
    st = idl.struct("Event")
    assert st.fields[1].name == "user"
    assert st.fields[9].ttype == 10          # typedef Timestamp -> i64
    assert st.fields[8].ttype == 8           # enum -> i32
    assert idl.enums["Color"] == {0: "BLUE", 2: "RED", 3: "GREEN"}
    assert st.fields[10].spec == "binary"


def test_golden_binary_struct(idl):
    # spec bytes: struct { 1: string "hi" } ->
    #   0x0B (string) 0x0001 (fid) 0x00000002 len "hi" 0x00 (stop)
    st = idl.struct("Inner")
    data = b"\x0b\x00\x01\x00\x00\x00\x02hi\x00"
    out = decode_struct(idl, st, _Reader(io.BytesIO(data)))
    assert out == {"label": "hi"}
    # our encoder emits the same bytes
    assert encode_struct(idl, st, {"label": "hi"}) == data
    # i64 field golden: 10:TYPE fid=2? use Event.clicks (fid 2, i64=0x0A)
    ev = idl.struct("Event")
    data2 = (b"\x0b\x00\x01\x00\x00\x00\x01u"         # user = "u"
             b"\x0a\x00\x02\xff\xff\xff\xff\xff\xff\xff\xd6"  # clicks = -42
             b"\x00")
    out2 = decode_struct(idl, ev, _Reader(io.BytesIO(data2)))
    assert out2 == {"user": "u", "clicks": -42}


def test_roundtrip_full_row(idl):
    st = idl.struct("Event")
    data = encode_struct(idl, st, ROW)
    out = decode_struct(idl, st, _Reader(io.BytesIO(data)))
    want = dict(ROW, tags=sorted(ROW["tags"]))
    out["tags"] = sorted(out["tags"])
    assert out == want


def test_unknown_fields_skipped(idl):
    """A producer with a NEWER schema (extra field 99): skipped, like
    generated thrift code does for unknown ids."""
    st = idl.struct("Inner")
    body = encode_struct(idl, st, {"label": "x"})
    # splice an unknown i32 field 99 before the stop byte
    evolved = body[:-1] + b"\x08\x00\x63\x00\x00\x00\x2a" + b"\x00"
    out = decode_struct(idl, st, _Reader(io.BytesIO(evolved)))
    assert out == {"label": "x"}


def test_truncation_raises(idl):
    st = idl.struct("Event")
    data = encode_struct(idl, st, ROW)
    with pytest.raises(ThriftError, match="truncated"):
        decode_struct(idl, st, _Reader(io.BytesIO(data[:-4])))


def test_record_reader_with_sidecars(tmp_path, idl):
    rows = [dict(ROW, user=f"u{i}", clicks=i, tags=[f"t{i}"])
            for i in range(40)]
    path = str(tmp_path / "ev.thrift.bin")
    write_structs(path, idl, idl.struct("Event"), rows)
    (tmp_path / "ev.thrift.bin.thrift").write_text(IDL)
    (tmp_path / "ev.thrift.bin.msg").write_text("Event")
    rdr = ThriftRecordReader(path)
    got = list(rdr.rows())
    assert len(got) == 40 and got[7]["user"] == "u7" and got[7]["clicks"] == 7
    assert got[0]["inner"] == {"label": "x", "weight": 0.5}
    # restartable like every reader
    assert len(list(rdr.rows())) == 40


def test_batch_ingestion_thrift_differential(tmp_path, idl):
    from pinot_tpu.cluster.enclosure import QuickCluster
    from pinot_tpu.ingest.batch import BatchIngestionJobSpec, run_batch_ingestion
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.table import TableConfig

    rng = np.random.default_rng(9)
    rows = [{"user": f"u{int(x) % 30}", "clicks": int(c),
             "cost": round(float(v), 3)}
            for x, c, v in zip(rng.integers(0, 30, 300),
                               rng.integers(0, 9, 300),
                               rng.uniform(0, 5, 300))]
    tpath = str(tmp_path / "ev.thrift")
    write_structs(tpath, idl, idl.struct("Event"), rows)
    (tmp_path / "ev.thrift.thrift").write_text(IDL)
    (tmp_path / "ev.thrift.msg").write_text("Event")
    jsonl = tmp_path / "ev.jsonl"
    jsonl.write_text("".join(json.dumps(r) + "\n" for r in rows))

    schema = Schema("ev", [dimension("user"),
                           metric("clicks", DataType.LONG),
                           metric("cost", DataType.DOUBLE)])
    results = {}
    for fmt, path in [("thrift", tpath), ("jsonl", str(jsonl))]:
        cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path / fmt))
        cfg = TableConfig("ev")
        cluster.create_table(schema, cfg)
        run_batch_ingestion(
            BatchIngestionJobSpec(input_paths=[path],
                                  table=cfg.table_name_with_type,
                                  segment_rows=120),
            cluster.controller, work_dir=str(tmp_path / f"w_{fmt}"))
        results[fmt] = cluster.query(
            "SELECT user, COUNT(*), SUM(clicks), SUM(cost) FROM ev "
            "GROUP BY user ORDER BY user LIMIT 100").rows
    assert results["thrift"] == results["jsonl"]


def test_realtime_stream_decoder(tmp_path, idl):
    from pinot_tpu.cluster.enclosure import QuickCluster
    from pinot_tpu.ingest.stream import MemoryStream, register_decoder
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.table import StreamConfig, TableConfig, TableType

    MemoryStream.reset_all()
    register_decoder("thrift_events", make_thrift_decoder(IDL, "Event"))
    try:
        cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
        schema = Schema("ev", [dimension("user"),
                               metric("clicks", DataType.LONG)])
        cfg = TableConfig("ev", table_type=TableType.REALTIME, replication=1,
                          stream=StreamConfig(stream_type="memory",
                                              topic="th_topic",
                                              decoder="thrift_events",
                                              flush_threshold_rows=1000))
        cluster.create_realtime_table(schema, cfg, 1)
        stream = MemoryStream.get("th_topic")
        st = idl.struct("Event")
        total = 0
        for i in range(150):
            total += i
            stream.produce(encode_struct(idl, st,
                                         {"user": f"u{i % 4}", "clicks": i}),
                           partition=0)
        cluster.pump_realtime(cfg.table_name_with_type)
        res = cluster.query("SELECT COUNT(*), SUM(clicks) FROM ev")
        assert res.rows[0] == [150, total]
    finally:
        MemoryStream.reset_all()


def test_review_nested_containers_and_hostile_nesting():
    """Review round: nested containers encode+decode; wire-controlled deep
    nesting in skipped fields raises ThriftError, never RecursionError;
    negative container sizes error instead of misaligning the stream."""
    idl = ThriftIDL("""
struct N {
  1: optional list<list<i32>> grid;
  2: optional map<string, list<double>> series;
}
""")
    st = idl.struct("N")
    row = {"grid": [[1, 2], [3]], "series": {"a": [0.5, 1.5]}}
    data = encode_struct(idl, st, row)
    assert decode_struct(idl, st, _Reader(io.BytesIO(data))) == row

    # hostile: unknown field with 2000 nested structs (3 bytes/level)
    deep = b"\x0c\x00\x63" + b"\x0c\x00\x01" * 2000
    with pytest.raises(ThriftError):
        decode_struct(idl, st, _Reader(io.BytesIO(
            data[:-1] + deep + b"\x00")))

    # hostile: unknown list with negative count must raise, not misalign
    bad = data[:-1] + b"\x0f\x00\x63\x08\xff\xff\xff\xff" + b"\x00"
    with pytest.raises(ThriftError, match="negative"):
        decode_struct(idl, st, _Reader(io.BytesIO(bad)))
