"""Ingestion & cluster health plane: consumer lag tracking, readiness probes,
controller ingestion verdicts, consuming-freshness query stats, periodic task
health, gauge history rings, and the cluster_top tool.

Reference scenarios: consumingSegmentsInfo + /tables/{t}/ingestionStatus
(PinotRealtimeTableResource), /health vs /health/readiness (ServiceStatus),
and the broker's Math.min reduce of minConsumingFreshnessTimeMs.
"""

import json
import time

import numpy as np
import pytest

from pinot_tpu.cluster.enclosure import QuickCluster
from pinot_tpu.ingest.stream import MemoryStream
from pinot_tpu.query import stats as qstats
from pinot_tpu.schema import DataType, Schema, date_time, dimension, metric
from pinot_tpu.table import StreamConfig, TableConfig, TableType
from pinot_tpu.utils.metrics import get_registry

from conftest import wait_until


@pytest.fixture(autouse=True)
def _reset_streams():
    MemoryStream.reset_all()
    yield
    MemoryStream.reset_all()


def rt_schema():
    return Schema("events", [
        dimension("user", DataType.STRING),
        metric("value", DataType.DOUBLE),
        date_time("ts", DataType.LONG),
    ])


def rt_config(flush_rows=200):
    return TableConfig(
        "events", table_type=TableType.REALTIME, replication=1,
        time_column="ts",
        stream=StreamConfig(stream_type="memory", topic="events_topic",
                            decoder="json", flush_threshold_rows=flush_rows))


def produce(partition, n, ts_base=None):
    ts_base = ts_base if ts_base is not None else int(time.time() * 1000)
    stream = MemoryStream.get("events_topic")
    for i in range(n):
        stream.produce(json.dumps({"user": f"u{i}", "value": float(i),
                                   "ts": ts_base + i}), partition=partition)


def rt_cluster(tmp_path, num_servers=2, flush_rows=200):
    cluster = QuickCluster(num_servers=num_servers, work_dir=str(tmp_path))
    cfg = rt_config(flush_rows)
    cluster.create_realtime_table(rt_schema(), cfg, num_partitions=2)
    return cluster, cfg


# ---------------------------------------------------------------------------
# Units: lag tracker, stats min-merge, gauge history, periodic task metrics
# ---------------------------------------------------------------------------

def test_consumer_lag_tracker_units():
    from pinot_tpu.ingest.realtime import ConsumerLagTracker
    tr = ConsumerLagTracker("events_REALTIME", 0)
    assert tr.rows_indexed == 0 and tr.last_consumed_ms is None
    tr.on_batch(10, 8, 1_700_000_000_000)
    assert tr.rows_indexed == 8
    assert tr.rows_filtered == 2
    assert tr.last_event_time_ms == 1_700_000_000_000
    assert tr.last_consumed_ms is not None
    # event-time high-water only moves forward
    tr.on_batch(5, 5, 1_600_000_000_000)
    assert tr.last_event_time_ms == 1_700_000_000_000
    assert tr.rows_indexed == 13
    # empty fetch: no last_consumed bump
    before = tr.last_consumed_ms
    tr.on_batch(0, 0, None)
    assert tr.last_consumed_ms == before
    tr.on_error()
    assert tr.errors == 1


def test_execution_stats_min_merge_units():
    a = qstats.ExecutionStats()
    a.set_min(qstats.MIN_CONSUMING_FRESHNESS_TIME_MS, 2000)
    a.set_min(qstats.MIN_CONSUMING_FRESHNESS_TIME_MS, 3000)   # loses
    assert a.counters[qstats.MIN_CONSUMING_FRESHNESS_TIME_MS] == 2000
    b = qstats.ExecutionStats()
    b.set_min(qstats.MIN_CONSUMING_FRESHNESS_TIME_MS, 1500)
    b.add(qstats.NUM_CONSUMING_SEGMENTS_QUERIED, 2)
    a.add(qstats.NUM_CONSUMING_SEGMENTS_QUERIED, 1)
    a.merge(b)
    # min-merged, not summed; counters still sum
    assert a.counters[qstats.MIN_CONSUMING_FRESHNESS_TIME_MS] == 1500
    assert a.counters[qstats.NUM_CONSUMING_SEGMENTS_QUERIED] == 3
    # a side missing the key must NOT zero it out
    c = qstats.ExecutionStats()
    c.merge(a)
    c.merge(qstats.ExecutionStats())
    assert c.counters[qstats.MIN_CONSUMING_FRESHNESS_TIME_MS] == 1500
    pub = c.to_public_dict()
    assert pub[qstats.MIN_CONSUMING_FRESHNESS_TIME_MS] == 1500
    assert isinstance(pub[qstats.MIN_CONSUMING_FRESHNESS_TIME_MS], int)
    # never zero-filled: a record that touched no consuming segment omits it
    empty_pub = qstats.ExecutionStats().to_public_dict()
    assert qstats.MIN_CONSUMING_FRESHNESS_TIME_MS not in empty_pub
    assert empty_pub[qstats.NUM_CONSUMING_SEGMENTS_QUERIED] == 0


def test_merge_segment_results_min_rule():
    from pinot_tpu.query.reduce import SegmentResult, merge_segment_results
    r1 = SegmentResult("selection", stats={
        "numDocsScanned": 10,
        qstats.MIN_CONSUMING_FRESHNESS_TIME_MS: 5000})
    r2 = SegmentResult("selection", stats={
        "numDocsScanned": 7,
        qstats.MIN_CONSUMING_FRESHNESS_TIME_MS: 4000})
    r3 = SegmentResult("selection", stats={"numDocsScanned": 3})
    merged = merge_segment_results([r1, r2, r3], aggs=[])
    assert merged.stats["numDocsScanned"] == 20
    assert merged.stats[qstats.MIN_CONSUMING_FRESHNESS_TIME_MS] == 4000


def test_gauge_history_ring_bounded():
    from pinot_tpu.utils.metrics import MetricsRegistry
    reg = MetricsRegistry()
    g = reg.gauge("pinot_server_realtime_offset_lag", {"table": "t"})
    for i in range(g.HISTORY_LEN + 60):
        g.set(i)
    hist = g.history()
    assert len(hist) == g.HISTORY_LEN          # bounded ring
    assert hist[-1][1] == g.HISTORY_LEN + 59   # newest kept
    assert hist[0][1] == 60                    # oldest evicted
    assert all(ts > 0 for ts, _v in hist)
    reg.gauge("pinot_broker_queries_g").set(1)
    series = reg.gauge_histories("pinot_server")
    assert list(series) == ["pinot_server_realtime_offset_lag{table=t}"]
    assert len(series["pinot_server_realtime_offset_lag{table=t}"]) == \
        g.HISTORY_LEN


def test_periodic_task_error_metrics():
    from pinot_tpu.utils.periodic import PeriodicTask, PeriodicTaskScheduler
    reg = get_registry()
    boom = PeriodicTask("BoomTask", 60.0,
                        lambda: (_ for _ in ()).throw(RuntimeError("nope")))
    base = reg.counter_value("pinot_periodic_task_errors", {"task": "BoomTask"})
    boom.run_once()
    boom.run_once()
    assert boom.run_count == 2 and boom.error_count == 2
    assert reg.counter_value("pinot_periodic_task_errors",
                             {"task": "BoomTask"}) == base + 2
    st = boom.stats()
    assert st["errorCount"] == 2 and st["lastError"] == "RuntimeError: nope"
    assert st["lastRunMs"] is not None
    # a clean run clears the stale error
    boom.fn = lambda: None
    boom.run_once()
    assert boom.stats()["lastError"] is None
    sched = PeriodicTaskScheduler()
    sched.register(boom)
    assert sched.stats()["BoomTask"]["runCount"] == 3


# ---------------------------------------------------------------------------
# In-proc cluster: lag growth, verdicts, pause/resume, stale gauges
# ---------------------------------------------------------------------------

def test_offset_lag_grows_and_degrades(tmp_path):
    cluster, cfg = rt_cluster(tmp_path)
    table = cfg.table_name_with_type
    produce(0, 20)
    produce(1, 20)
    cluster.pump_realtime(table)
    st = cluster.controller.ingestion_status(table)
    assert st["ingestionState"] == "HEALTHY" and st["maxOffsetLag"] == 0
    assert st["numConsumingSegments"] == 2

    # consumers stall (nothing pumps): upstream offsets run ahead
    produce(0, 30)
    st = cluster.controller.ingestion_status(table)
    assert st["maxOffsetLag"] == 30
    assert st["ingestionState"] == "HEALTHY"     # under the default threshold
    cluster.catalog.put_property(
        "clusterConfig/controller.ingestion.offset.lag.threshold", "10")
    st = cluster.controller.ingestion_status(table)
    assert st["ingestionState"] == "DEGRADED"
    assert any("offset lag" in r for r in st["reasons"])
    # catching up clears the verdict
    cluster.pump_realtime(table)
    st = cluster.controller.ingestion_status(table)
    assert st["ingestionState"] == "HEALTHY" and st["reasons"] == []
    # per-partition server gauges exist with the lag detail
    seg_stats = next(iter(st["servers"].values()))["segments"]
    any_seg = next(iter(seg_stats.values()))
    assert any_seg["currentOffset"] is not None
    assert any_seg["latestStreamOffset"] is not None
    assert any_seg["offsetLag"] == 0


def test_pause_degrades_resume_heals(tmp_path):
    cluster, cfg = rt_cluster(tmp_path)
    table = cfg.table_name_with_type
    produce(0, 10)
    cluster.pump_realtime(table)
    assert cluster.controller.ingestion_status(table)["ingestionState"] == \
        "HEALTHY"
    cluster.controller.llc.pause_consumption(table)
    st = cluster.controller.ingestion_status(table)
    assert st["ingestionState"] == "DEGRADED"
    assert st["paused"] is True
    assert any("paused" in r for r in st["reasons"])
    cluster.controller.llc.resume_consumption(table)
    cluster.pump_realtime(table)
    st = cluster.controller.ingestion_status(table)
    assert st["ingestionState"] == "HEALTHY" and st["paused"] is False


def test_ingestion_gauges_and_stale_removal(tmp_path):
    cluster, cfg = rt_cluster(tmp_path)
    table = cfg.table_name_with_type
    produce(0, 5)
    cluster.pump_realtime(table)
    assert cluster.controller.run_ingestion_status_check() == \
        {table: "HEALTHY"}
    snap = get_registry().snapshot()
    key = f"pinot_controller_ingestion_healthy{{table={table}}}"
    assert snap[key] == 1
    assert f"pinot_controller_ingestion_offset_lag{{table={table}}}" in snap
    # cached rollup feeds the controller /debug view (no per-server detail)
    dbg = cluster.controller.debug_stats()
    assert dbg["ingestionStatus"][table]["ingestionState"] == "HEALTHY"
    assert "servers" not in dbg["ingestionStatus"][table]
    assert "IngestionStatusChecker" in dbg["periodicTasks"]

    cluster.controller.drop_table(table)
    assert cluster.controller.run_ingestion_status_check() == {}
    snap = get_registry().snapshot()
    assert key not in snap
    assert f"pinot_controller_ingestion_offset_lag{{table={table}}}" not in snap
    assert f"pinot_controller_ingestion_freshness_lag_ms{{table={table}}}" \
        not in snap


def test_server_lag_gauges_removed_on_stop(tmp_path):
    cluster, cfg = rt_cluster(tmp_path)
    table = cfg.table_name_with_type
    produce(0, 5)
    cluster.pump_realtime(table)
    cluster.servers[0].ingestion_snapshot()      # exports per-partition gauges
    assert any(k.startswith("pinot_server_realtime_offset_lag")
               for k in get_registry().snapshot())
    cluster.controller.drop_table(table)
    assert wait_until(
        lambda: not any(k.startswith("pinot_server_realtime_offset_lag")
                        for k in get_registry().snapshot()),
        timeout=10.0, interval=0.05, swallow=())


def test_consuming_query_stats_min_merge_in_proc(tmp_path):
    """Two partitions with different event-time high-waters: the response's
    minConsumingFreshnessTimeMs is the MIN across consuming segments (stalest
    wins), while numConsumingSegmentsQueried sums."""
    cluster, cfg = rt_cluster(tmp_path)
    table = cfg.table_name_with_type
    now = int(time.time() * 1000)
    produce(0, 10, ts_base=now - 10)             # fresh partition
    produce(1, 10, ts_base=now - 60_000)         # stale partition
    cluster.pump_realtime(table)
    res = cluster.query("SELECT COUNT(*) FROM events LIMIT 5")
    assert res.rows[0][0] == 20
    assert res.stats["numConsumingSegmentsQueried"] == 2
    assert res.stats["minConsumingFreshnessTimeMs"] == now - 60_000 + 9
    # an offline-only query carries no freshness key at all
    assert "minConsumingFreshnessTimeMs" not in \
        qstats.ExecutionStats().to_public_dict()


def test_ingestion_status_unknown_and_offline_tables(tmp_path):
    cluster, cfg = rt_cluster(tmp_path)
    with pytest.raises(ValueError):
        cluster.controller.ingestion_status("nope_REALTIME")
    schema = Schema("off", [dimension("site", DataType.STRING),
                            metric("v", DataType.DOUBLE)])
    cluster.create_table(schema, TableConfig("off"))
    st = cluster.controller.ingestion_status("off_OFFLINE")
    assert st["ingestionState"] == "HEALTHY"
    assert "offline" in st["message"]
    # offline tables never get ingestion gauges
    cluster.controller.run_ingestion_status_check()
    assert "pinot_controller_ingestion_healthy{table=off_OFFLINE}" not in \
        get_registry().snapshot()


# ---------------------------------------------------------------------------
# HTTP plane: health split, /debug/consuming, ingestionStatus, E2E demo
# ---------------------------------------------------------------------------

def test_health_and_ingestion_over_http(tmp_path):
    """The acceptance-criteria demo over real HTTP: (a) /health liveness vs
    /health/readiness gating, (b) ingestionStatus DEGRADED with a lag reason
    while paused / HEALTHY after resume, (c) a query over consuming segments
    returning numConsumingSegmentsQueried + min-merged
    minConsumingFreshnessTimeMs on the HTTP transport."""
    from pinot_tpu.cluster.broker import Broker
    from pinot_tpu.cluster.catalog import CONSUMING, Catalog
    from pinot_tpu.cluster.controller import Controller
    from pinot_tpu.cluster.deepstore import LocalDeepStore
    from pinot_tpu.cluster.http_service import HttpError, get_json, http_call
    from pinot_tpu.cluster.server import ServerNode
    from pinot_tpu.cluster.services import (BrokerService, ControllerService,
                                            ServerService)

    catalog = Catalog()
    controller = Controller("controller_0", catalog,
                            LocalDeepStore(str(tmp_path / "ds")),
                            str(tmp_path / "ctrl"))
    csvc = ControllerService(controller)
    services = [csvc]
    try:
        nodes = [ServerNode(f"server_{i}", catalog,
                            LocalDeepStore(str(tmp_path / "ds")),
                            str(tmp_path / f"server_{i}"),
                            completion=controller.llc) for i in range(2)]
        for n in nodes:
            services.append(ServerService(n))
        broker = Broker("broker_0", catalog)
        bsvc = BrokerService(broker)
        services.append(bsvc)
        surl = services[1].url

        # (a) liveness vs readiness: a ghost ideal-state assignment makes
        # server_0 not data-ready — /health stays 200, readiness goes 503
        assert get_json(f"{surl}/health")["instance"] == "server_0"
        assert get_json(f"{surl}/health/readiness")["ready"] is True
        with catalog._lock:
            catalog.ideal_state.setdefault("ghost_REALTIME", {})[
                "ghost__0__0__x"] = {"server_0": CONSUMING}
        assert get_json(f"{surl}/health")["status"] == "UP"   # still alive
        with pytest.raises(HttpError) as ei:
            http_call("GET", f"{surl}/health/readiness", timeout=5.0)
        assert ei.value.status == 503
        with catalog._lock:
            del catalog.ideal_state["ghost_REALTIME"]
        assert get_json(f"{surl}/health/readiness")["ready"] is True

        # realtime table over the shared catalog; consumers attach in-proc
        controller.add_schema(rt_schema())
        cfg = rt_config()
        MemoryStream.create("events_topic", 2)
        controller.add_realtime_table(cfg, num_partitions=2)
        table = cfg.table_name_with_type
        now = int(time.time() * 1000)
        produce(0, 10, ts_base=now - 10)
        produce(1, 10, ts_base=now - 60_000)
        for n in nodes:
            mgr = n.realtime_manager(table)
            if mgr is not None:
                mgr.pump_all()

        # (c) consuming stats over the HTTP transport (broker scatters to the
        # servers' /query routes registered from advertised instance ports)
        def http_count():
            try:
                r = json.loads(http_call(
                    "POST", f"{bsvc.url}/query",
                    json.dumps({"sql": "SELECT COUNT(*) FROM events LIMIT 5"}
                               ).encode()).decode())
                rows = r["resultTable"]["rows"]
                return r if rows and rows[0][0] == 20 else None
            except Exception:
                return None
        assert wait_until(lambda: http_count() is not None,
                          timeout=20.0, interval=0.2, swallow=())
        resp = http_count()
        # the merged stats record is spread at the response top level
        assert resp["numConsumingSegmentsQueried"] == 2
        assert resp["minConsumingFreshnessTimeMs"] == now - 60_000 + 9

        # server /debug/consuming: per-segment offsets + lag over HTTP
        snap = get_json(f"{surl}/debug/consuming")
        assert snap["instance"] == "server_0"
        segs = snap["tables"][table]["segments"]
        assert all(s["currentOffset"] is not None for s in segs.values())

        # (b) ingestionStatus over HTTP: HEALTHY -> paused DEGRADED with a
        # reason -> HEALTHY after resume (controller polls the servers' own
        # /debug/consuming routes)
        st = get_json(f"{csvc.url}/tables/{table}/ingestionStatus")
        assert st["ingestionState"] == "HEALTHY"
        assert st["numConsumingSegments"] == 2
        controller.llc.pause_consumption(table)
        # stall some backlog behind the paused table for the lag detail
        produce(0, 25)
        catalog.put_property(
            "clusterConfig/controller.ingestion.offset.lag.threshold", "10")
        st = get_json(f"{csvc.url}/tables/{table}/ingestionStatus")
        assert st["ingestionState"] == "DEGRADED"
        assert any("paused" in r for r in st["reasons"])
        controller.llc.resume_consumption(table)
        for n in nodes:
            mgr = n.realtime_manager(table)
            if mgr is not None:
                mgr.pump_all()
        st = get_json(f"{csvc.url}/tables/{table}/ingestionStatus")
        assert st["ingestionState"] == "HEALTHY", st["reasons"]

        # controller + server /debug rollups over HTTP
        cdbg = get_json(f"{csvc.url}/debug")
        assert "IngestionStatusChecker" in cdbg["periodicTasks"]
        sdbg = get_json(f"{surl}/debug")
        assert "gaugeHistories" in sdbg
        # 404 for an unknown table's ingestionStatus
        with pytest.raises(HttpError) as ei:
            http_call("GET", f"{csvc.url}/tables/nope_REALTIME/ingestionStatus",
                      timeout=5.0)
        assert ei.value.status == 404
    finally:
        for s in services:
            s.stop()


# ---------------------------------------------------------------------------
# cluster_top tool (pure snapshot/render with an injected fetcher)
# ---------------------------------------------------------------------------

def test_cluster_top_snapshot_and_render():
    from pinot_tpu.tools.cluster_top import render, snapshot
    pages = {
        "http://c/tables": {"tables": ["ev_REALTIME", "off_OFFLINE"]},
        "http://c/tables/ev_REALTIME/ingestionStatus": {
            "table": "ev_REALTIME", "ingestionState": "DEGRADED",
            "reasons": ["consumption is paused"], "paused": True,
            "numConsumingSegments": 2, "maxOffsetLag": 12345,
            "maxFreshnessLagMs": 90_000, "totalRowsPerSecond": 42.5},
        "http://c/tables/off_OFFLINE/ingestionStatus": {
            "table": "off_OFFLINE", "ingestionState": "HEALTHY",
            "reasons": [], "numConsumingSegments": 0, "maxOffsetLag": 0,
            "maxFreshnessLagMs": 0, "totalRowsPerSecond": 0.0},
        "http://c/debug": {"periodicTasks": {
            "SegmentStatusChecker": {"errorCount": 0, "lastError": None},
            "RetentionManager": {"errorCount": 3,
                                 "lastError": "RuntimeError: boom"}}},
        "http://b/debug": {"queryStats": {"numQueries": 7, "avgTimeMs": 3.2,
                                          "numSlowQueries": 1}},
    }
    snap = snapshot("http://c", "http://b", pages.__getitem__)
    assert set(snap["tables"]) == {"ev_REALTIME", "off_OFFLINE"}
    assert snap["broker"]["numQueries"] == 7
    out = render(snap)
    assert "ev_REALTIME" in out and "DEGRADED" in out and "HEALTHY" in out
    assert "12345" in out               # offset lag column
    assert "1.5m" in out                # 90s freshness lag, humanized
    assert "queries=7" in out
    assert "RetentionManager" in out and "boom" in out
    assert "SegmentStatusChecker" not in out.split("RetentionManager")[1]

    # endpoint failures degrade to partial data, not a crash
    def flaky(url):
        if url.endswith("/debug"):
            raise OSError("connection refused")
        return pages[url]
    snap2 = snapshot("http://c", "http://b", flaky)
    assert len(snap2["errors"]) == 2    # broker + controller debug both down
    assert "DEGRADED" in render(snap2)


def test_cluster_top_render_empty():
    from pinot_tpu.tools.cluster_top import render
    out = render({"tables": {}, "broker": None, "errors": ["controller: x"],
                  "periodicTasks": {}})
    assert "(no tables)" in out and "controller: x" in out
