"""Minion as a REAL process role: claim over controller REST, inputs via the
deep-store proxy, outputs via segment upload / atomic replace — zero in-proc
shortcuts (reference: MinionStarter + Helix task framework, here the process
spawned by `python -m pinot_tpu.cluster.process --role minion`).
"""

import json
import time

import numpy as np
import pytest

from pinot_tpu.cluster.http_service import get_json, post_json
from pinot_tpu.cluster.process import ProcessCluster
from pinot_tpu.minion.tasks import MERGE_ROLLUP, REALTIME_TO_OFFLINE
from pinot_tpu.schema import DataType, Schema, date_time, dimension, metric
from pinot_tpu.segment.writer import SegmentBuilder
from pinot_tpu.table import StreamConfig, TableConfig, TableType

from conftest import wait_until

DAY = 24 * 3600 * 1000


def event_schema():
    return Schema("events", [
        dimension("site", DataType.STRING),
        metric("clicks", DataType.LONG),
        metric("cost", DataType.DOUBLE),
        date_time("ts", DataType.LONG),
    ])


def make_cols(rng, n, day_ms):
    return {
        "site": rng.choice(["a.com", "b.com", "c.com"], n).tolist(),
        "clicks": rng.integers(1, 10, n),
        "cost": np.round(rng.uniform(0.1, 5.0, n), 3),
        "ts": day_ms + rng.integers(0, DAY, n),
    }


def _tasks(cluster, **q):
    qs = "&".join(f"{k}={v}" for k, v in q.items())
    return get_json(f"{cluster.controller_url}/tasks" + (f"?{qs}" if qs else ""))[
        "tasks"]


def test_merge_rollup_executes_on_minion_process(tmp_path):
    """Full distributed flow: controller generates, the MINION PROCESS claims
    through REST, downloads inputs through the deep-store proxy, merges, and
    swaps via the atomic replaceSegments endpoint — queries never see a
    half-state and totals are unchanged."""
    schema = event_schema()
    yesterday = (int(time.time() * 1000) // DAY - 1) * DAY
    rng = np.random.default_rng(31)
    with ProcessCluster(num_servers=1, num_minions=1,
                        work_dir=str(tmp_path)) as cluster:
        cluster.controller.add_schema(schema)
        cfg = TableConfig(schema.name, time_column="ts",
                          task_configs={MERGE_ROLLUP: {"bucketMs": DAY}})
        cluster.controller.add_table(cfg)
        builder = SegmentBuilder(schema)
        for i in range(3):
            seg = builder.build(make_cols(rng, 100, yesterday),
                                str(tmp_path / "build"), f"events_{i}")
            cluster.controller.upload_segment(cfg.table_name_with_type, seg)

        def count():
            rows = cluster.query(
                "SELECT COUNT(*), SUM(clicks) FROM events")["resultTable"]["rows"]
            return tuple(rows[0]) if rows else (0, 0)
        assert wait_until(lambda: count()[0] == 300, timeout=30)
        before = count()

        post_json(f"{cluster.controller_url}/tasks/generate", {})
        assert wait_until(lambda: any(
            t["state"] == "COMPLETED" and t["task_type"] == MERGE_ROLLUP
            for t in _tasks(cluster)), timeout=60), _tasks(cluster)

        # the merged segment replaced the three inputs atomically
        def seg_names():
            return list(cluster.controller.segments_meta(
                cfg.table_name_with_type)["segments"])
        assert wait_until(
            lambda: len(seg_names()) == 1 and seg_names()[0].startswith("merged_"),
            timeout=30), seg_names()
        assert wait_until(lambda: count() == before, timeout=30), \
            (count(), before)
        done = [t for t in _tasks(cluster) if t["state"] == "COMPLETED"]
        assert done[0]["worker"] == "minion_0"  # the PROCESS did the work


def test_realtime_to_offline_over_processes(tmp_path):
    """Hybrid flow with every role a real process: realtime consumption over a
    TCP log broker, commit over HTTP, the minion process moving a closed
    window into the OFFLINE half, the broker's time boundary keeping counts
    exact throughout."""
    from pinot_tpu.ingest.kafkalite import LogBrokerClient, LogBrokerServer
    schema = event_schema()
    day0 = (int(time.time() * 1000) // DAY - 3) * DAY
    srv = LogBrokerServer()
    try:
        client = LogBrokerClient(srv.bootstrap)
        client.create_topic("events_topic", 1)
        with ProcessCluster(num_servers=1, num_minions=1,
                            work_dir=str(tmp_path)) as cluster:
            cluster.controller.add_schema(schema)
            off_cfg = TableConfig(schema.name, table_type=TableType.OFFLINE,
                                  time_column="ts")
            cluster.controller.add_table(off_cfg)
            rt_cfg = TableConfig(
                schema.name, table_type=TableType.REALTIME, time_column="ts",
                stream=StreamConfig(stream_type="kafkalite",
                                    topic="events_topic",
                                    properties={"bootstrap": srv.bootstrap},
                                    flush_threshold_rows=40),
                task_configs={REALTIME_TO_OFFLINE: {"bucketMs": DAY}})
            cluster.controller.add_table(rt_cfg, num_partitions=1)

            rng = np.random.default_rng(37)
            total = 0
            for day in (day0, day0 + DAY, day0 + 2 * DAY):
                cols = make_cols(rng, 40, day)
                for i in range(40):
                    client.produce("events_topic", json.dumps(
                        {k: (v[i].item() if isinstance(v[i], np.generic)
                             else v[i]) for k, v in cols.items()}))
                total += 40

            def count():
                rows = cluster.query(
                    "SELECT COUNT(*) FROM events")["resultTable"]["rows"]
                return rows[0][0] if rows else 0
            assert wait_until(lambda: count() == total, timeout=40), count()
            before = count()

            post_json(f"{cluster.controller_url}/tasks/generate", {})
            assert wait_until(lambda: any(
                t["state"] == "COMPLETED"
                and t["task_type"] == REALTIME_TO_OFFLINE
                for t in _tasks(cluster)), timeout=60), _tasks(cluster)

            def offline_segments():
                try:
                    return cluster.controller.segments_meta(
                        off_cfg.table_name_with_type)["segments"]
                except Exception:
                    return {}
            assert wait_until(lambda: len(offline_segments()) >= 1, timeout=30)
            # hybrid count never double-counts across the time boundary
            assert wait_until(lambda: count() == before, timeout=30), \
                (count(), before)
    finally:
        srv.stop()


def test_dead_minion_lease_requeues_to_live_worker(tmp_path):
    """A worker that claimed a task and died: the lease gc requeues it, the
    live minion process completes it, and the dead worker's late finish is
    FENCED (ignored) — no loss, no double-apply."""
    schema = event_schema()
    yesterday = (int(time.time() * 1000) // DAY - 1) * DAY
    rng = np.random.default_rng(41)
    # slow the live minion's claim polling: the test's "dead" worker must
    # win the claim race right after generate (with the default 1s poll the
    # live minion occasionally steals the task under suite load)
    conf = tmp_path / "minion.conf"
    conf.write_text("minion.poll.seconds=30\n")
    with ProcessCluster(num_servers=1, num_minions=1,
                        work_dir=str(tmp_path),
                        config_path=str(conf)) as cluster:
        cluster.controller.add_schema(schema)
        cfg = TableConfig(schema.name, time_column="ts",
                          task_configs={MERGE_ROLLUP: {"bucketMs": DAY}})
        cluster.controller.add_table(cfg)
        builder = SegmentBuilder(schema)
        for i in range(2):
            seg = builder.build(make_cols(rng, 60, yesterday),
                                str(tmp_path / "build"), f"events_{i}")
            cluster.controller.upload_segment(cfg.table_name_with_type, seg)

        def count():
            rows = cluster.query(
                "SELECT COUNT(*), SUM(cost) FROM events")["resultTable"]["rows"]
            return tuple(rows[0]) if rows else (0, 0.0)
        assert wait_until(lambda: count()[0] == 120, timeout=30)
        before = count()

        # a "dead" worker claims the generated task and never finishes
        post_json(f"{cluster.controller_url}/tasks/generate", {})
        claimed = post_json(f"{cluster.controller_url}/tasks/claim",
                            {"worker": "minion_dead",
                             "taskTypes": [MERGE_ROLLUP]})["task"]
        assert claimed is not None and claimed["worker"] == "minion_dead"

        # lease expires -> gc requeues -> the LIVE minion process completes it
        post_json(f"{cluster.controller_url}/tasks/gc", {"leaseMs": 0})
        assert wait_until(lambda: any(
            t["state"] == "COMPLETED" and t["worker"] == "minion_0"
            for t in _tasks(cluster)), timeout=60), _tasks(cluster)

        # the dead worker's late completion must not apply (fencing)
        resp = post_json(f"{cluster.controller_url}/tasks/finish",
                         {"taskId": claimed["task_id"],
                          "worker": "minion_dead", "error": ""})
        assert resp["applied"] is False

        # differential: data identical after the merge
        assert wait_until(lambda: count()[0] == before[0], timeout=30)
        assert count()[1] == pytest.approx(before[1], rel=1e-6)
        segs = cluster.controller.segments_meta(
            cfg.table_name_with_type)["segments"]
        assert len(segs) == 1 and next(iter(segs)).startswith("merged_")


def test_multistage_join_groupby_on_worker_processes(tmp_path):
    """VERDICT item 5 'done' shape: a join + GROUP BY over two tables executes
    with scan, join, AND partial-aggregation stages on server PROCESSES
    (streamed stage frames over chunked HTTP), differential-checked against
    sqlite3."""
    import os
    import sqlite3

    rng = np.random.default_rng(43)
    orders_schema = Schema("orders", [
        dimension("region", DataType.STRING),
        metric("custkey", DataType.LONG),
        metric("amount", DataType.DOUBLE),
    ])
    cust_schema = Schema("customer", [
        dimension("segment", DataType.STRING),
        metric("key", DataType.LONG),
    ])
    n_orders, n_cust = 600, 40
    orders = {
        "region": rng.choice(["NA", "EU", "APAC"], n_orders).tolist(),
        "custkey": rng.integers(0, n_cust, n_orders),
        "amount": np.round(rng.uniform(1.0, 100.0, n_orders), 2),
    }
    cust = {
        "segment": rng.choice(["AUTO", "RETAIL"], n_cust).tolist(),
        "key": np.arange(n_cust),
    }

    with ProcessCluster(num_servers=2, work_dir=str(tmp_path)) as cluster:
        cluster.controller.add_schema(orders_schema)
        cluster.controller.add_schema(cust_schema)
        cluster.controller.add_table(TableConfig("orders"))
        cluster.controller.add_table(TableConfig("customer"))
        b = SegmentBuilder(orders_schema)
        for i in range(2):
            half = {k: v[i * 300:(i + 1) * 300] for k, v in orders.items()}
            cluster.controller.upload_segment(
                "orders_OFFLINE",
                b.build(half, str(tmp_path / "bo"), f"orders_{i}"))
        cluster.controller.upload_segment(
            "customer_OFFLINE",
            SegmentBuilder(cust_schema).build(cust, str(tmp_path / "bc"),
                                              "customer_0"))
        assert wait_until(lambda: cluster.query(
            "SELECT COUNT(*) FROM orders")["resultTable"]["rows"][0][0] == 600,
            timeout=30)

        sql = ("SELECT c.segment, o.region, COUNT(*), SUM(o.amount) "
               "FROM orders o JOIN customer c ON o.custkey = c.key "
               "GROUP BY c.segment, o.region "
               "ORDER BY c.segment, o.region LIMIT 100")
        resp = cluster.query(sql)
        # r3 asserted the funnel path's worker aggregation; r4's mailbox
        # shuffle supersedes it (aggregation runs on stage workers AND the
        # data never transits the broker) — accept either stat
        assert resp.get("workerAggregation") or resp.get("mailboxShuffle"), \
            sorted(resp.keys())
        got = [tuple(r) for r in resp["resultTable"]["rows"]]

        # differential oracle
        db = sqlite3.connect(":memory:")
        db.execute("CREATE TABLE orders (region TEXT, custkey INT, amount REAL)")
        db.execute("CREATE TABLE customer (segment TEXT, key INT)")
        db.executemany("INSERT INTO orders VALUES (?,?,?)",
                       list(zip(orders["region"],
                                orders["custkey"].tolist(),
                                orders["amount"].tolist())))
        db.executemany("INSERT INTO customer VALUES (?,?)",
                       list(zip(cust["segment"], cust["key"].tolist())))
        want = db.execute(
            "SELECT c.segment, o.region, COUNT(*), SUM(o.amount) "
            "FROM orders o JOIN customer c ON o.custkey = c.key "
            "GROUP BY c.segment, o.region "
            "ORDER BY c.segment, o.region").fetchall()
        assert [(g[0], g[1], g[2]) for g in got] == \
            [(w[0], w[1], w[2]) for w in want]
        for g, w in zip(got, want):
            assert g[3] == pytest.approx(w[3], rel=1e-9)

        # the join+agg stages genuinely ran on the server processes: their
        # join-stage meters moved (streamed /stage dispatches)
        total_stages = 0
        for sid in ("server_0", "server_1"):
            with open(os.path.join(cluster.run_dir, f"{sid}.ready")) as f:
                url = json.load(f)["url"]
            metrics = __import__("urllib.request", fromlist=["request"]).urlopen(
                f"{url}/metrics", timeout=10).read().decode()
            for line in metrics.splitlines():
                if line.startswith("pinot_server_join_stages"):
                    total_stages += float(line.split()[-1])
        assert total_stages > 0


def test_distributed_batch_ingestion_over_minions(tmp_path):
    """POST /ingestJobs splits a batch job into per-file tasks; minion
    PROCESSES ingest the files in parallel and push segments — the
    hadoop/spark-runner analog over the minion fleet."""
    import csv

    schema = Schema("pages", [
        dimension("site", DataType.STRING),
        metric("clicks", DataType.LONG),
        date_time("ts", DataType.LONG),
    ])
    rng = np.random.default_rng(53)
    files, total_clicks, total_rows = [], 0, 0
    for i in range(3):
        path = tmp_path / f"in_{i}.csv"
        with open(path, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(["site", "clicks", "ts"])
            for j in range(200):
                clicks = int(rng.integers(1, 50))
                w.writerow([f"s{j % 7}.com", clicks, 1_700_000_000_000 + j])
                total_clicks += clicks
                total_rows += 1
        files.append(str(path))

    with ProcessCluster(num_servers=1, num_minions=2,
                        work_dir=str(tmp_path / "cluster")) as cluster:
        cluster.controller.add_schema(schema)
        cluster.controller.add_table(TableConfig("pages"))
        resp = post_json(f"{cluster.controller_url}/ingestJobs",
                         {"table": "pages_OFFLINE", "inputPaths": files})
        assert len(resp["tasks"]) == 3

        def states():
            return {t["task_id"]: t for t in _tasks(cluster)
                    if t["task_type"] == "SegmentGenerationAndPushTask"}
        assert wait_until(lambda: all(
            t["state"] == "COMPLETED" for t in states().values())
            and len(states()) == 3, timeout=60), states()

        def count():
            rows = cluster.query("SELECT COUNT(*), SUM(clicks) FROM pages")[
                "resultTable"]["rows"]
            return tuple(rows[0]) if rows else (0, 0)
        assert wait_until(lambda: count() == (total_rows, total_clicks),
                          timeout=30), count()
        # tasks ran on the minion fleet (real processes)
        workers = {t["worker"] for t in states().values()}
        assert workers <= {"minion_0", "minion_1"} and workers
        # segments carry the provenance custom marks
        metas = cluster.controller.segments_meta("pages_OFFLINE")["segments"]
        assert len(metas) == 3
        assert all(m["custom"]["task"] == "SegmentGenerationAndPushTask"
                   for m in metas.values())


def test_convert_to_raw_index_round_trips(tmp_path):
    """ConvertToRawIndexTask (VERDICT r4 #8): the controller generates, a
    MINION PROCESS claims and rewrites the segment with raw forward
    indexes, the lineage swap lands, queries stay correct, and the served
    replacement genuinely lost its dictionaries."""
    from pinot_tpu.minion.tasks import CONVERT_TO_RAW_INDEX
    from pinot_tpu.segment.reader import load_segment

    schema = event_schema()
    rng = np.random.default_rng(7)
    with ProcessCluster(num_servers=1, num_minions=1,
                        work_dir=str(tmp_path)) as cluster:
        cluster.controller.add_schema(schema)
        cfg = TableConfig(schema.name, time_column="ts", task_configs={
            CONVERT_TO_RAW_INDEX: {"columnsToConvert": ["cost", "clicks"]}})
        cluster.controller.add_table(cfg)
        cols = make_cols(rng, 500, 0)
        want_cost = float(np.sum(cols["cost"]))
        b = SegmentBuilder(schema)
        cluster.controller.upload_segment(
            cfg.table_name_with_type,
            b.build(cols, str(tmp_path / "b"), "events_0"))

        def count():
            rows = cluster.query("SELECT COUNT(*) FROM events")[
                "resultTable"]["rows"]
            return rows[0][0] if rows else 0
        assert wait_until(lambda: count() == 500, timeout=60)

        # the generator runs on the controller's periodic task loop
        def converted():
            metas = cluster.controller.segments_meta(
                cfg.table_name_with_type)["segments"]
            return [n for n, m in metas.items()
                    if m.get("custom", {}).get("task") == CONVERT_TO_RAW_INDEX]
        assert wait_until(lambda: len(converted()) == 1, timeout=90), \
            "conversion task never landed"
        new_name = converted()[0]
        assert new_name.startswith("events_0_raw_")
        # totals survive the swap exactly
        rows = cluster.query("SELECT COUNT(*), SUM(cost) FROM events")[
            "resultTable"]["rows"]
        assert rows[0][0] == 500
        assert abs(rows[0][1] - want_cost) < 1e-6 * max(1.0, want_cost)
        # the replacement segment's converted columns have NO dictionary
        # (download it from the deep store like a server would)
        import tempfile

        from pinot_tpu.cluster.deepstore import untar_segment
        meta = cluster.controller.segments_meta(
            cfg.table_name_with_type)["segments"][new_name]
        tar = tmp_path / "check.tar.gz"
        from pinot_tpu.cluster.http_service import http_call
        data = http_call(
            "GET", f"{cluster.controller_url}/deepstore/"
            f"{meta['download_path']}")
        tar.write_bytes(data)
        seg = load_segment(untar_segment(str(tar), str(tmp_path / "chk")))
        assert not seg.column("cost").has_dictionary
        assert not seg.column("clicks").has_dictionary
        assert seg.column("site").has_dictionary  # untouched column keeps it
        # no further tasks generate for the already-converted segment
        time.sleep(2)
        assert len(converted()) == 1
