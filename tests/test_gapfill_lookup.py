"""Gapfill post-processing + LOOKUP dimension-table joins.

Reference: `GapfillProcessor` reduce-side time-bucket filling and
`DimensionTableDataManager`/`LookupTransformFunction` scan-time lookup joins.
"""

import numpy as np
import pytest

from pinot_tpu.query.context import QueryValidationError, compile_query
from pinot_tpu.query.executor import execute_query
from pinot_tpu.query.lookup import REGISTRY, DimensionTable
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.schema import DataType, Schema, dimension, metric


@pytest.fixture(scope="module")
def tseg(tmp_path_factory):
    schema = Schema("events", [dimension("ts", DataType.LONG),
                               dimension("host", DataType.STRING),
                               metric("v", DataType.DOUBLE)])
    # buckets of 10; host a has data at 0,10,30; host b at 10,20
    cols = {
        "ts": np.array([0, 10, 30, 10, 20], dtype=np.int64),
        "host": ["a", "a", "a", "b", "b"],
        "v": np.array([1.0, 2.0, 3.0, 5.0, 6.0]),
    }
    out = tmp_path_factory.mktemp("gap")
    return [load_segment(SegmentBuilder(schema).build(cols, str(out), "ev_0"))]


def test_gapfill_previous_value(tseg):
    r = execute_query(
        tseg,
        "SELECT GAPFILL(ts, 0, 40, 10), host, FILL(SUM(v), 'FILL_PREVIOUS_VALUE') "
        "FROM events GROUP BY ts, host LIMIT 100")
    rows = {(row[1], row[0]): row[2] for row in r.rows}
    assert rows[("a", 0)] == 1.0
    assert rows[("a", 10)] == 2.0
    assert rows[("a", 20)] == 2.0   # filled with previous
    assert rows[("a", 30)] == 3.0
    assert rows[("b", 0)] is None   # nothing before the first real bucket
    assert rows[("b", 10)] == 5.0
    assert rows[("b", 20)] == 6.0
    assert rows[("b", 30)] == 6.0   # filled
    assert len(r.rows) == 8         # 2 series x 4 buckets


def test_gapfill_default_value(tseg):
    r = execute_query(
        tseg,
        "SELECT GAPFILL(ts, 0, 40, 10), host, FILL(SUM(v), 'FILL_DEFAULT_VALUE', 0) "
        "FROM events GROUP BY ts, host LIMIT 100")
    rows = {(row[1], row[0]): row[2] for row in r.rows}
    assert rows[("a", 20)] == 0
    assert rows[("b", 0)] == 0


def test_gapfill_unfilled_is_null(tseg):
    r = execute_query(
        tseg,
        "SELECT GAPFILL(ts, 0, 40, 10), host, SUM(v), COUNT(*) "
        "FROM events GROUP BY ts, host LIMIT 100")
    rows = {(row[1], row[0]): (row[2], row[3]) for row in r.rows}
    assert rows[("a", 20)] == (None, None)


def test_gapfill_validation(tseg):
    with pytest.raises(QueryValidationError, match="GAPFILL"):
        compile_query("SELECT GAPFILL(ts, 0, 40) FROM events GROUP BY ts")
    with pytest.raises(QueryValidationError, match="FILL requires"):
        compile_query("SELECT ts, FILL(SUM(v), 'FILL_DEFAULT_VALUE') "
                      "FROM events GROUP BY ts")


# ---------------------------------------------------------------------------
# LOOKUP
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def lookup_env(tmp_path_factory):
    REGISTRY.register(DimensionTable(
        "dim_hosts", ["hostname"],
        {"hostname": np.array(["a", "b", "c"], dtype=object),
         "dc": np.array(["us-east", "eu-west", "us-east"], dtype=object),
         "cores": np.array([8, 16, 32], dtype=np.int64)}))
    schema = Schema("metrics", [dimension("host", DataType.STRING),
                                metric("load", DataType.DOUBLE)])
    cols = {"host": ["a", "b", "a", "x"],
            "load": np.array([0.5, 0.6, 0.7, 0.9])}
    out = tmp_path_factory.mktemp("lkp")
    return [load_segment(SegmentBuilder(schema).build(cols, str(out), "m_0"))]


def test_lookup_selection(lookup_env):
    r = execute_query(
        lookup_env,
        "SELECT host, LOOKUP('dim_hosts', 'dc', 'hostname', host), load "
        "FROM metrics LIMIT 10")
    got = {tuple(row[:2]) for row in r.rows}
    assert ("a", "us-east") in got
    assert ("b", "eu-west") in got
    assert ("x", None) in got  # lookup miss -> null


def test_lookup_group_by(lookup_env):
    r = execute_query(
        lookup_env,
        "SELECT LOOKUP('dim_hosts', 'dc', 'hostname', host), SUM(load) "
        "FROM metrics GROUP BY LOOKUP('dim_hosts', 'dc', 'hostname', host) LIMIT 10")
    rows = {row[0]: row[1] for row in r.rows}
    assert rows["us-east"] == pytest.approx(1.2)
    assert rows["eu-west"] == pytest.approx(0.6)


def test_lookup_numeric_value(lookup_env):
    r = execute_query(
        lookup_env,
        "SELECT SUM(LOOKUP('dim_hosts', 'cores', 'hostname', host)) "
        "FROM metrics WHERE host <> 'x' LIMIT 10")
    assert r.rows[0][0] == pytest.approx(8 + 16 + 8)


def test_lookup_in_cluster(tmp_path):
    """Dimension table loaded through the server path on table creation."""
    from pinot_tpu.cluster.enclosure import QuickCluster
    from pinot_tpu.table import TableConfig

    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    dim_schema = Schema("countries",
                        [dimension("code", DataType.STRING),
                         dimension("continent", DataType.STRING)],
                        primary_key_columns=["code"])
    fact_schema = Schema("visits", [dimension("code", DataType.STRING),
                                    metric("n", DataType.INT)])
    dim_cfg = cluster.create_table(dim_schema, TableConfig("countries",
                                                           is_dim_table=True))
    fact_cfg = cluster.create_table(fact_schema, TableConfig("visits"))
    cluster.ingest_columns(dim_cfg, {"code": ["de", "fr", "jp"],
                                     "continent": ["EU", "EU", "AS"]})
    cluster.ingest_columns(fact_cfg, {"code": ["de", "fr", "jp", "de"],
                                      "n": np.array([1, 2, 3, 4], dtype=np.int32)})
    r = cluster.query(
        "SELECT LOOKUP('countries', 'continent', 'code', code), SUM(n) FROM visits "
        "GROUP BY LOOKUP('countries', 'continent', 'code', code) ORDER BY 1 LIMIT 10")
    assert [list(row) for row in r.rows] == [["AS", 3.0], ["EU", 7.0]]
