"""Device-memory observability plane tests: the HBM ledger, its gauges, the
controller's per-table memory verdicts, per-kernel cost profiles in query
stats, Chrome-trace memory counters, and a ledger-backed leak regression.

The ledger is the accounting substrate (utils/memledger.py) — these tests pin
its arithmetic exactly (byte-accurate totals, filter semantics, re-registration
replacement), then prove the plane end to end: staging through the engine shows
up in `/debug/memory`, the controller turns server headroom into
HEALTHY/DEGRADED/UNHEALTHY, and unloading a segment returns the ledger to
baseline (the leak gate `bench.py --memory` enforces continuously).
"""

import json
import threading

import numpy as np
import pytest

from pinot_tpu.utils import memledger
from pinot_tpu.utils.memledger import (MemoryLedger, get_ledger, reset_ledger,
                                       staged)
from pinot_tpu.utils.metrics import get_registry

from conftest import make_ssb_columns


@pytest.fixture()
def ledger(monkeypatch):
    """A MemoryLedger with a deterministic 1000-byte capacity (exact headroom
    math) publishing into a freshly reset process registry."""
    monkeypatch.setenv("PINOT_TPU_HBM_CAPACITY_BYTES", "1000")
    get_registry().reset()
    led = MemoryLedger()
    yield led
    get_registry().reset()


def _gauge_value(name, **labels):
    """Find one gauge in the registry snapshot by name + label pairs (label
    render order is an implementation detail; match pairs individually)."""
    for key, v in get_registry().snapshot().items():
        if key == name:
            return v
        if key.startswith(name + "{") and all(
                f"{lk}={lv}" in key for lk, lv in labels.items()):
            return v
    return None


# -- ledger arithmetic --------------------------------------------------------

def test_register_release_and_filters(ledger):
    ledger.register("t1", "seg_a", "raw", "col_x", 100)
    ledger.register("t1", "seg_a", "dict", "col_x", 40)
    ledger.register("t1", "seg_b", "raw", "col_x", 60)
    ledger.register("t2", "seg_c", "raw", "col_y", 9)
    assert ledger.resident_bytes() == 209
    assert ledger.resident_bytes(table="t1") == 200
    assert ledger.resident_bytes(segment="seg_a") == 140
    assert ledger.resident_bytes(kind="raw") == 169
    assert ledger.resident_bytes(table="t1", kind="raw") == 160
    # release by segment returns exactly what that segment held
    assert ledger.release(segment="seg_a") == 140
    assert ledger.resident_bytes() == 69
    # release by table sweeps the remainder of t1
    assert ledger.release(table="t1") == 60
    assert ledger.resident_bytes() == 9
    assert ledger.release() == 9
    assert ledger.resident_bytes() == 0


def test_reregistration_replaces_not_accumulates(ledger):
    """Idempotent re-staging (a cache rebuild) must not double-count."""
    ledger.register("t1", "seg_a", "raw", "col_x", 100)
    ledger.register("t1", "seg_a", "raw", "col_x", 100)
    assert ledger.resident_bytes() == 100
    # a rebuild at a different size replaces the old accounting
    ledger.register("t1", "seg_a", "raw", "col_x", 250)
    assert ledger.resident_bytes() == 250
    assert ledger.release(segment="seg_a") == 250


def test_table_attribution_binding_and_llc_fallback(ledger):
    # explicit binding wins (offline segment names carry no table prefix)
    ledger.bind_segment("trips_OFFLINE", "trips_0")
    ledger.register(None, "trips_0", "raw", "fare", 10)
    assert ledger.resident_bytes(table="trips_OFFLINE") == 10
    # LLC names embed the table: {table}__{partition}__{seq}__{creation}
    ledger.register(None, "lineorder__0__3__20240101", "consuming", "rows", 7)
    assert ledger.resident_bytes(table="lineorder") == 7
    # neither binding nor LLC shape: attributed to the "-" bucket, not lost
    ledger.register(None, "orphan_seg", "raw", "c", 5)
    assert ledger.resident_bytes(table="-") == 5
    assert ledger.resident_bytes() == 22
    # releasing a segment also drops its binding; re-registering the same
    # segment name falls back to the LLC/"-" resolution
    ledger.release(segment="trips_0")
    ledger.register(None, "trips_0", "raw", "fare", 10)
    assert ledger.resident_bytes(table="trips_OFFLINE") == 0
    assert ledger.resident_bytes(table="-") == 15


def test_snapshot_shape_and_headroom(ledger):
    ledger.register("t1", "seg_a", "raw", "col_x", 300)
    ledger.register("t1", "seg_b", "dict", "col_x", 100)
    ledger.register("t2", "seg_c", "raw", "col_y", 200)
    ledger.note_transient(50)
    snap = ledger.snapshot()
    assert snap["totalBytes"] == 600
    assert snap["entries"] == 3
    assert snap["capacityBytes"] == 1000
    assert snap["capacityEstimated"] is False   # env override is exact
    assert snap["headroomPct"] == 40.0
    assert snap["transientPeakBytes"] == 50
    # watermark tracks resident + transient peak, with a timestamped history
    assert snap["watermarkBytes"] == 650
    assert snap["watermarkHistory"]
    ts, bytes_ = snap["watermarkHistory"][-1]
    assert bytes_ == 650 and ts > 0
    assert snap["kinds"] == {"raw": 500, "dict": 100}
    assert snap["tables"] == {"t1": 400, "t2": 200}
    # topSegments sorted by bytes descending
    top = snap["topSegments"]
    assert [e["segment"] for e in top] == ["seg_a", "seg_c", "seg_b"]
    assert top[0] == {"table": "t1", "segment": "seg_a", "bytes": 300}
    # snapshot must be JSON-serializable as-is (it IS the /debug/memory body)
    json.dumps(snap)


def test_note_transient_tracks_peak_only(ledger):
    ledger.note_transient(100)
    ledger.note_transient(40)    # below peak: ignored
    ledger.note_transient(120)
    assert ledger.snapshot()["transientPeakBytes"] == 120
    assert _gauge_value("pinot_server_hbm_transient_peak_bytes") == 120


def test_reconcile_drift_math(ledger, monkeypatch):
    ledger.register("t1", "seg_a", "raw", "c", 800)
    # device view = baseline (untracked compile constants) + tracked staging
    monkeypatch.setattr(memledger, "live_device_bytes", lambda: 1000)
    rec = ledger.reconcile(baseline_bytes=200)
    assert rec["ledgerBytes"] == 800
    assert rec["deviceBytes"] == 1000
    assert rec["driftBytes"] == 0 and rec["driftPct"] == 0.0
    # a leak on the device side shows as positive drift
    monkeypatch.setattr(memledger, "live_device_bytes", lambda: 1200)
    rec = ledger.reconcile(baseline_bytes=200)
    assert rec["driftBytes"] == 200
    assert rec["driftPct"] == pytest.approx(20.0)
    # runtime can't enumerate live arrays: drift is None, not a fake zero
    monkeypatch.setattr(memledger, "live_device_bytes", lambda: None)
    rec = ledger.reconcile()
    assert rec["driftBytes"] is None and rec["driftPct"] is None


def test_concurrent_registration_is_exact(ledger):
    """N threads staging disjoint entries: the total must be byte-exact —
    the ledger is the reconciliation source of truth, so a lost update would
    masquerade as device-side drift."""
    threads_n, per_thread, nbytes = 8, 200, 10

    def work(tid):
        for i in range(per_thread):
            ledger.register("t", f"seg_{tid}", "raw", f"col_{i}", nbytes)

    threads = [threading.Thread(target=work, args=(t,))
               for t in range(threads_n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert ledger.resident_bytes() == threads_n * per_thread * nbytes
    freed = sum(ledger.release(segment=f"seg_{t}") for t in range(threads_n))
    assert freed == threads_n * per_thread * nbytes
    assert ledger.resident_bytes() == 0


# -- gauge exposition ---------------------------------------------------------

def test_gauges_flush_after_register_burst(ledger):
    """The register hot path throttles gauge publishing; internal accounting
    is always exact and flush()/snapshot()/release() force the gauges
    current."""
    ledger.register("t1", "seg_a", "raw", "c1", 100)   # first publish is free
    ledger.register("t1", "seg_a", "dict", "c1", 40)   # within throttle window
    assert ledger.resident_bytes() == 140               # accounting: exact now
    ledger.flush()
    assert _gauge_value("pinot_server_hbm_resident_bytes",
                        table="t1", kind="raw") == 100
    assert _gauge_value("pinot_server_hbm_resident_bytes",
                        table="t1", kind="dict") == 40
    assert _gauge_value("pinot_server_hbm_resident_total_bytes") == 140
    assert _gauge_value("pinot_server_hbm_capacity_bytes") == 1000
    assert _gauge_value("pinot_server_hbm_headroom_pct") == 86.0


def test_stale_series_removed_on_release(ledger):
    """A dropped table/kind must not keep exporting a zero series forever —
    the same stale-gauge hygiene the controller checkers follow."""
    ledger.register("t1", "seg_a", "raw", "c1", 100)
    ledger.flush()
    assert _gauge_value("pinot_server_hbm_resident_bytes",
                        table="t1", kind="raw") == 100
    ledger.release(table="t1")
    assert _gauge_value("pinot_server_hbm_resident_bytes",
                        table="t1", kind="raw") is None
    assert _gauge_value("pinot_server_hbm_resident_total_bytes") == 0


def test_staged_wrapper_registers_and_passes_through(monkeypatch):
    """staged() is THE sanctioned staging wrapper (the graftcheck rule
    enforces it): registers nbytes in the process ledger, returns the array
    unchanged."""
    get_registry().reset()
    reset_ledger()
    try:
        arr = np.zeros(256, dtype=np.float64)
        out = staged(arr, "seg_w", "raw", name="col", table="tw")
        assert out is arr
        assert get_ledger().resident_bytes(table="tw", kind="raw") == arr.nbytes
        # objects without nbytes register 0 rather than raising mid-staging
        token = staged(object(), "seg_w", "dict", table="tw")
        assert token is not None
        assert get_ledger().resident_bytes(table="tw") == arr.nbytes
    finally:
        reset_ledger()
        get_registry().reset()


# -- controller memory verdicts ----------------------------------------------

@pytest.fixture()
def verdict_cluster(tmp_path, ssb_schema):
    from pinot_tpu.cluster import QuickCluster
    from pinot_tpu.table import TableConfig
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    cfg = TableConfig(ssb_schema.name, replication=1,
                      time_column="lo_orderdate")
    cluster.create_table(ssb_schema, cfg)
    return cluster, cfg.table_name_with_type


def _poller(headroom, tables=None, total=None):
    snap = {"headroomPct": headroom, "tables": tables or {},
            "totalBytes": total if total is not None
            else sum((tables or {}).values())}
    return lambda: snap


def _raising_poller():
    def poll():
        raise ConnectionError("server down")
    return poll


def test_memory_verdict_matrix(verdict_cluster):
    """The full HEALTHY/DEGRADED/UNHEALTHY decision table off the
    `controller.memory.headroom.pct` threshold (default 20%)."""
    cluster, table = verdict_cluster
    ctl = cluster.controller

    # comfortable headroom -> HEALTHY, bytes attributed per server
    ctl.memory_pollers = {"server_0": _poller(80.0, {table: 4096})}
    assert ctl.run_memory_check() == {table: "HEALTHY"}
    st = ctl.memory_status(table)
    assert st["memoryState"] == "HEALTHY" and st["reasons"] == []
    assert st["residentBytes"] == 4096
    assert st["servers"] == {"server_0": 4096}
    assert st["minServerHeadroomPct"] == 80.0

    # below threshold -> DEGRADED, reason names the server and the threshold
    ctl.memory_pollers = {"server_0": _poller(10.0, {table: 4096})}
    assert ctl.run_memory_check() == {table: "DEGRADED"}
    st = ctl.memory_status(table)
    assert any("server_0" in r and "20" in r for r in st["reasons"])

    # at/below a quarter of the threshold -> UNHEALTHY (critically low)
    ctl.memory_pollers = {"server_0": _poller(4.0, {table: 4096})}
    assert ctl.run_memory_check() == {table: "UNHEALTHY"}
    assert any("critically" in r
               for r in ctl.memory_status(table)["reasons"])

    # fully out of HBM -> UNHEALTHY even when the threshold is tiny
    cluster.catalog.put_property(
        "clusterConfig/controller.memory.headroom.pct", "1")
    ctl.memory_pollers = {"server_0": _poller(0.0, {table: 4096})}
    assert ctl.run_memory_check() == {table: "UNHEALTHY"}

    # threshold override: 40% headroom breaches a raised 50% bar
    cluster.catalog.put_property(
        "clusterConfig/controller.memory.headroom.pct", "50")
    ctl.memory_pollers = {"server_0": _poller(40.0, {table: 4096})}
    assert ctl.run_memory_check() == {table: "DEGRADED"}
    assert ctl.memory_status(table)["headroomThresholdPct"] == 50.0


def test_memory_verdict_unreachable_servers(verdict_cluster):
    cluster, table = verdict_cluster
    ctl = cluster.controller

    # every poller raising: no data at all -> UNHEALTHY, not silently healthy
    ctl.memory_pollers = {"server_0": _raising_poller()}
    assert ctl.run_memory_check() == {table: "UNHEALTHY"}
    st = ctl.memory_status(table)
    assert any("no server reported" in r for r in st["reasons"])
    assert st["unreachableServers"] == ["server_0"]

    # one healthy + one unreachable -> DEGRADED (partial visibility)
    ctl.memory_pollers = {"server_0": _poller(90.0, {table: 1024}),
                          "server_1": _raising_poller()}
    assert ctl.run_memory_check() == {table: "DEGRADED"}
    st = ctl.memory_status(table)
    assert any("poll failed" in r for r in st["reasons"])
    # residency still sums over the servers that did report
    assert st["residentBytes"] == 1024

    # resident bytes sum ACROSS servers when several report the same table
    ctl.memory_pollers = {"server_0": _poller(90.0, {table: 1024}),
                          "server_1": _poller(70.0, {table: 512})}
    assert ctl.run_memory_check() == {table: "HEALTHY"}
    st = ctl.memory_status(table)
    assert st["residentBytes"] == 1536
    assert st["minServerHeadroomPct"] == 70.0


def test_memory_status_unknown_and_prejudgment(verdict_cluster):
    cluster, table = verdict_cluster
    ctl = cluster.controller
    # before the first check: UNKNOWN, never a fabricated verdict
    ctl._memory_status = {}
    st = ctl.memory_status(table)
    assert st["memoryState"] == "UNKNOWN"
    ctl.memory_pollers = {"server_0": _poller(80.0, {table: 10})}
    ctl.run_memory_check()
    assert ctl.memory_status(table)["memoryState"] == "HEALTHY"
    # verdicts key on nameWithType here; the bare logical name is still a
    # known table, so it answers UNKNOWN rather than 404ing
    assert ctl.memory_status("lineorder")["memoryState"] in (
        "UNKNOWN", "HEALTHY")
    with pytest.raises(ValueError):
        ctl.memory_status("no_such_table")


def test_memory_check_publishes_and_removes_gauges(verdict_cluster):
    cluster, table = verdict_cluster
    ctl = cluster.controller
    ctl.memory_pollers = {"server_0": _poller(35.5, {table: 2048})}
    ctl.run_memory_check()
    assert _gauge_value("pinot_controller_hbm_headroom_pct",
                        instance="server_0") == 35.5
    assert _gauge_value("pinot_controller_hbm_healthy", table=table) == 1
    assert _gauge_value("pinot_controller_hbm_resident_bytes",
                        table=table) == 2048
    # server departs: its instance series must disappear, not freeze
    ctl.memory_pollers = {"server_1": _poller(60.0, {table: 2048})}
    ctl.run_memory_check()
    assert _gauge_value("pinot_controller_hbm_headroom_pct",
                        instance="server_0") is None
    assert _gauge_value("pinot_controller_hbm_headroom_pct",
                        instance="server_1") == 60.0


# -- cost profiles + end-to-end ledger (in-proc) ------------------------------

@pytest.fixture()
def lineorder_cluster(tmp_path, ssb_schema):
    from pinot_tpu.cluster import QuickCluster
    from pinot_tpu.table import TableConfig
    rng = np.random.default_rng(11)
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    cfg = TableConfig(ssb_schema.name, replication=1,
                      time_column="lo_orderdate")
    cluster.create_table(ssb_schema, cfg)
    cluster.ingest_columns(cfg, make_ssb_columns(rng, 2000))
    return cluster, cfg


def test_query_stats_carry_cost_profile(lineorder_cluster):
    """EXPLAIN-ANALYZE-grade cost fields ride every query response: modeled
    flops + bytes from XLA cost_analysis (or its deterministic input-bytes
    fallback) and the achieved-vs-nominal HBM roofline percentage."""
    cluster, cfg = lineorder_cluster
    res = cluster.query("SELECT SUM(lo_revenue), COUNT(*) FROM lineorder")
    stats = res.stats
    assert stats["deviceBytesAccessed"] > 0
    assert stats["deviceFlops"] >= 0
    assert 0.0 <= stats["rooflinePct"] <= 100.0
    # counters accumulate across launches; the roofline is max-merged so it
    # stays a percentage even over multi-segment scatter
    res2 = cluster.query(
        "SELECT lo_region, SUM(lo_revenue) FROM lineorder "
        "GROUP BY lo_region LIMIT 10")
    assert res2.stats["deviceBytesAccessed"] > 0
    assert 0.0 <= res2.stats["rooflinePct"] <= 100.0


def test_query_staging_lands_in_ledger_and_verdict(lineorder_cluster):
    """End to end in-proc: running a query stages columns, the ledger
    attributes them to the table, and the controller verdict sees the bytes."""
    cluster, cfg = lineorder_cluster
    table = cfg.table_name_with_type
    ledger = get_ledger()
    before = ledger.resident_bytes(table=table)
    cluster.query("SELECT SUM(lo_extendedprice) FROM lineorder")
    assert ledger.resident_bytes(table=table) > before
    snap = cluster.servers[0].memory_snapshot()
    assert snap["instanceId"] == "server_0"
    assert snap["tables"].get(table, 0) > 0
    verdicts = cluster.controller.run_memory_check()
    assert verdicts[table] in ("HEALTHY", "DEGRADED", "UNHEALTHY")
    st = cluster.controller.memory_status(table)
    assert st["residentBytes"] >= snap["tables"][table]


def test_segment_unload_returns_ledger_to_baseline(lineorder_cluster):
    """The leak regression: block_for/release_block cycles and a table-manager
    remove_segment must return the ledger exactly to baseline (this is the
    gate `bench.py --memory` runs over 100 cycles)."""
    from pinot_tpu.engine import datablock
    cluster, cfg = lineorder_cluster
    table = cfg.table_name_with_type
    mgr = cluster.servers[0].tables[table]
    segments = mgr.acquire()
    assert segments
    seg = segments[0]
    try:
        ledger = get_ledger()
        datablock.release_block(seg)
        baseline = ledger.resident_bytes(segment=seg.name)
        staged_bytes = None
        for _ in range(5):
            blk = datablock.block_for(seg)
            blk.valid
            blk.ids("lo_region")
            blk.values("lo_quantity")
            now = ledger.resident_bytes(segment=seg.name)
            assert now > baseline
            if staged_bytes is None:
                staged_bytes = now
            # idempotent re-staging must not grow the ledger
            assert now == staged_bytes
            datablock.release_block(seg)
            assert ledger.resident_bytes(segment=seg.name) == baseline
        # unload path: remove_segment DEFERS the block drop while this test
        # still holds an acquired ref (the unload-vs-in-flight-query fix) —
        # the device block stays alive until the last release()
        datablock.block_for(seg).ids("lo_region")
        assert ledger.resident_bytes(segment=seg.name) > baseline
        mgr.remove_segment(seg.name)
        assert ledger.resident_bytes(segment=seg.name) > baseline
    finally:
        mgr.release(segments)
    # the release that drained the refcount freed block + ledger entries
    assert ledger.resident_bytes(segment=seg.name) == 0


# -- HTTP transport: /debug/memory, memoryStatus, cost fields -----------------

@pytest.fixture()
def http_cluster(tmp_path):
    """Controller + 1 server + 1 broker over real HTTP (test_mux idiom) with
    a loaded two-segment trips table."""
    from pinot_tpu.cluster.broker import Broker
    from pinot_tpu.cluster.catalog import Catalog
    from pinot_tpu.cluster.controller import Controller
    from pinot_tpu.cluster.deepstore import LocalDeepStore
    from pinot_tpu.cluster.process import ControllerClient
    from pinot_tpu.cluster.remote import ControllerDeepStore, RemoteCatalog
    from pinot_tpu.cluster.server import ServerNode
    from pinot_tpu.cluster.services import (BrokerService, ControllerService,
                                            ServerService)
    from pinot_tpu.schema import DataType, FieldSpec, Schema
    from pinot_tpu.segment.writer import SegmentBuilder, SegmentGeneratorConfig
    from pinot_tpu.table import TableConfig
    from conftest import wait_until

    schema = Schema("trips", [FieldSpec("city", DataType.STRING),
                              FieldSpec("fare", DataType.DOUBLE),
                              FieldSpec("n", DataType.INT)])
    catalog = Catalog()
    deepstore = LocalDeepStore(str(tmp_path / "deepstore"))
    controller = Controller("controller_0", catalog, deepstore,
                            str(tmp_path / "ctrl"))
    csvc = ControllerService(controller)
    services = [csvc]
    catalogs = []
    try:
        src = RemoteCatalog(csvc.url, poll_timeout_s=1.0)
        catalogs.append(src)
        node = ServerNode("server_0", src, ControllerDeepStore(csvc.url),
                          str(tmp_path / "server_0"))
        ssvc = ServerService(node)
        services.append(ssvc)
        brc = RemoteCatalog(csvc.url, poll_timeout_s=1.0)
        catalogs.append(brc)
        bsvc = BrokerService(Broker("broker_0", brc))
        services.append(bsvc)

        c = ControllerClient(csvc.url)
        c.add_schema(schema)
        cfg = TableConfig("trips", replication=1)
        c.add_table(cfg)
        builder = SegmentBuilder(schema, SegmentGeneratorConfig())
        for i, (cities, fares, ns) in enumerate((
                (["nyc", "sf", "nyc", "la"], [10.0, 20.0, 30.0, 7.5],
                 [1, 2, 3, 4]),
                (["sf", "la", "nyc"], [5.0, 7.0, 2.5], [5, 6, 7]))):
            seg = builder.build(
                {"city": np.array(cities, dtype=object),
                 "fare": np.array(fares, dtype=np.float64),
                 "n": np.array(ns, dtype=np.int32)},
                str(tmp_path / f"b{i}"), f"trips_{i}")
            c.upload_segment(cfg.table_name_with_type, seg)
        assert wait_until(
            lambda: len(node.segments_served(cfg.table_name_with_type)) == 2,
            timeout=15.0, interval=0.05, swallow=())
        yield {"csvc": csvc, "ssvc": ssvc, "bsvc": bsvc,
               "controller": controller, "table": cfg.table_name_with_type}
    finally:
        for rc in catalogs:
            rc.close()
        for s in services:
            s.stop()


def test_memory_plane_over_http(http_cluster):
    """The whole plane through real sockets: cost fields in broker responses,
    the server's /debug/memory ledger panel, and the controller's
    memoryStatus verdict fed by its HTTP /debug/memory poller."""
    from pinot_tpu.cluster.http_service import get_json
    from pinot_tpu.cluster.process import BrokerClient
    from conftest import wait_until

    bc = BrokerClient(http_cluster["bsvc"].url)
    assert wait_until(
        lambda: bc.query("SELECT COUNT(*) FROM trips"
                         )["resultTable"]["rows"][0][0] == 7,
        timeout=15.0, interval=0.1)
    # stats keys ride at the top level of the broker response (Pinot style)
    resp = bc.query("SELECT SUM(fare) FROM trips")
    assert resp["deviceBytesAccessed"] > 0
    assert "deviceFlops" in resp
    assert 0.0 <= resp.get("rooflinePct", 0.0) <= 100.0

    # the server's ledger panel shows the staged columns, attributed
    snap = get_json(f"{http_cluster['ssvc'].url}/debug/memory")
    assert snap["instanceId"] == "server_0"
    assert snap["totalBytes"] > 0
    assert snap["tables"].get(http_cluster["table"], 0) > 0
    assert 0.0 <= snap["headroomPct"] <= 100.0
    assert snap["capacityBytes"] > 0

    # controller polls the HTTP route (no in-proc poller registered here)
    verdicts = http_cluster["controller"].run_memory_check()
    assert http_cluster["table"] in verdicts
    st = get_json(f"{http_cluster['csvc'].url}"
                  f"/tables/{http_cluster['table']}/memoryStatus")
    assert st["memoryState"] in ("HEALTHY", "DEGRADED", "UNHEALTHY")
    assert st["residentBytes"] >= snap["tables"][http_cluster["table"]]
    assert "server_0" in st["servers"]


# -- Chrome-trace memory counters ---------------------------------------------

def test_chrome_trace_memory_counter_events():
    """HBM residency rides the trace timeline as Chrome counter events
    (`ph: "C"`, cat "memory") so chrome://tracing renders a filled residency
    track under the query spans."""
    from pinot_tpu.cluster.broker import Broker
    from pinot_tpu.utils.trace import to_chrome_trace

    samples = Broker._memory_samples(5.0)
    assert samples and samples[0]["tsMs"] == 5.0
    series = samples[0]["series"]
    assert set(series) == {"hbm_resident_bytes", "hbm_transient_peak_bytes"}
    entry = {"traceId": "t-mem", "sql": "SELECT 1", "timeUsedMs": 5.0,
             "spans": [{"name": "broker", "startMs": 0.0, "durationMs": 5.0,
                        "depth": 0}],
             "memory": samples}
    doc = to_chrome_trace(entry)
    counters = [e for e in doc["traceEvents"] if e.get("ph") == "C"]
    assert {e["name"] for e in counters} == set(series)
    for ev in counters:
        assert ev["cat"] == "memory"
        assert ev["ts"] == 5000.0          # ms -> µs on the span timebase
        assert "bytes" in ev["args"]
        assert ev["args"]["bytes"] == series[ev["name"]]
    # span events are untouched by the counter track
    assert any(e.get("ph") == "X" and e["name"] == "broker"
               for e in doc["traceEvents"])


def test_trace_without_memory_samples_has_no_counters():
    from pinot_tpu.utils.trace import to_chrome_trace
    doc = to_chrome_trace({"traceId": "t0", "spans": [
        {"name": "broker", "startMs": 0.0, "durationMs": 1.0, "depth": 0}]})
    assert not [e for e in doc["traceEvents"] if e.get("ph") == "C"]
