"""S3-wire deep store: protocol client + stub, sigv4, cluster integration.

Mirrors the reference's S3 plugin coverage
(`pinot-plugins/pinot-file-system/pinot-s3/src/test/.../S3PinotFSTest.java`,
which runs against an in-process S3 mock the same way) plus chaos: a full
ProcessCluster storing segments through the s3 scheme, surviving a stub
outage via peer download and healing after recovery.
"""

import json
import os
import time

import numpy as np
import pytest

from pinot_tpu.cluster.deepstore import create_fs
from pinot_tpu.cluster.s3store import (S3DeepStoreFS, S3Error, S3StubServer,
                                       sign_request, sigv4_canonical,
                                       sigv4_signature, sigv4_string_to_sign)
from pinot_tpu.schema import DataType, Schema, date_time, dimension, metric
from pinot_tpu.table import StreamConfig, TableConfig, TableType

from conftest import wait_until


@pytest.fixture
def stub():
    s = S3StubServer(bucket="pinot", access_key="AKIATEST",
                     secret_key="sekrit")
    yield s
    s.stop()


# -- FS contract -------------------------------------------------------------

def test_s3_fs_contract(stub, tmp_path):
    fs = create_fs(stub.spec())
    assert isinstance(fs, S3DeepStoreFS)
    # put/get bytes
    fs.put_bytes(b"hello", "t/seg0.tar.gz")
    assert fs.get_bytes("t/seg0.tar.gz") == b"hello"
    assert fs.exists("t/seg0.tar.gz")
    assert fs.exists("t")            # prefix-exists, like MemDeepStore
    assert not fs.exists("t/nope")
    # upload/download files
    src = tmp_path / "blob"
    src.write_bytes(b"\x00\x01" * 1000)
    fs.upload(str(src), "t/seg1.tar.gz")
    dst = tmp_path / "out" / "blob"
    fs.download("t/seg1.tar.gz", str(dst))
    assert dst.read_bytes() == src.read_bytes()
    # listdir with delimiter semantics
    fs.put_bytes(b"x", "t/sub/inner.bin")
    assert fs.listdir("t") == ["seg0.tar.gz", "seg1.tar.gz", "sub"]
    # move (copy+delete like S3PinotFS) and delete
    fs.move("t/seg0.tar.gz", "moved/seg0.tar.gz")
    assert not fs.exists("t/seg0.tar.gz")
    assert fs.get_bytes("moved/seg0.tar.gz") == b"hello"
    fs.delete("t")                    # recursive prefix delete
    assert not fs.exists("t/seg1.tar.gz")
    assert not fs.exists("t/sub/inner.bin")
    with pytest.raises(FileNotFoundError):
        fs.get_bytes("t/seg1.tar.gz")


def test_s3_prefix_scoping(stub):
    a = create_fs(stub.spec("clusterA"))
    b = create_fs(stub.spec("clusterB"))
    a.put_bytes(b"A", "k")
    b.put_bytes(b"B", "k")
    assert a.get_bytes("k") == b"A" and b.get_bytes("k") == b"B"
    assert "clusterA/k" in stub.objects and "clusterB/k" in stub.objects


# -- sigv4 -------------------------------------------------------------------

def test_sigv4_self_golden():
    """Pinned signature: any change to the canonicalization breaks loudly."""
    canonical, signed = sigv4_canonical(
        "GET", "/pinot/t/seg.tar.gz", "list-type=2&prefix=t%2F",
        "127.0.0.1:9000", "20260730T120000Z",
        "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855")
    assert signed == "host;x-amz-content-sha256;x-amz-date"
    sts = sigv4_string_to_sign(canonical, "20260730T120000Z", "us-east-1")
    sig = sigv4_signature("sekrit", "us-east-1", "20260730T120000Z", sts)
    assert sig == sigv4_signature("sekrit", "us-east-1", "20260730T120000Z",
                                  sts)  # deterministic
    assert len(sig) == 64 and int(sig, 16) >= 0
    headers = sign_request("GET", "http://127.0.0.1:9000/pinot/k", b"",
                           "AKIATEST", "sekrit", "us-east-1",
                           amz_date="20260730T120000Z")
    assert headers["Authorization"].startswith(
        "AWS4-HMAC-SHA256 Credential=AKIATEST/20260730/us-east-1/s3/"
        "aws4_request, SignedHeaders=host;x-amz-content-sha256;x-amz-date, "
        "Signature=")


def test_sigv4_bad_credentials_rejected(stub):
    good = create_fs(stub.spec())
    good.put_bytes(b"x", "k")           # correct creds accepted
    bad = create_fs(f"s3://pinot?endpoint={stub.url}"
                    f"&accessKey=AKIATEST&secretKey=WRONG")
    with pytest.raises(S3Error, match="SignatureDoesNotMatch"):
        bad.put_bytes(b"x", "k2")
    unsigned = create_fs(f"s3://pinot?endpoint={stub.url}")
    with pytest.raises(S3Error, match="SignatureDoesNotMatch"):
        unsigned.get_bytes("k")


def test_tampered_payload_rejected(stub):
    """The signature binds the payload hash: replaying headers with a
    different body must fail."""
    import urllib.request
    headers = sign_request("PUT", f"{stub.url}/pinot/k", b"original",
                           "AKIATEST", "sekrit", "us-east-1")
    req = urllib.request.Request(f"{stub.url}/pinot/k", data=b"tampered",
                                 method="PUT", headers=headers)
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=5)
    assert ei.value.code == 403


# -- cluster integration -----------------------------------------------------

def test_cluster_lifecycle_on_s3(stub, tmp_path):
    """Upload -> assignment -> server download -> query -> delete, all
    through the S3 wire (mirror of the mem-FS lifecycle test)."""
    from pinot_tpu.cluster.broker import Broker
    from pinot_tpu.cluster.catalog import Catalog
    from pinot_tpu.cluster.controller import Controller
    from pinot_tpu.cluster.server import ServerNode
    from pinot_tpu.segment.writer import SegmentBuilder

    fs = create_fs(stub.spec("deepstore"))
    catalog = Catalog()
    ctrl = Controller("c0", catalog, fs, str(tmp_path / "ctrl"))
    server = ServerNode("server_0", catalog, fs, str(tmp_path / "s0"),
                        completion=ctrl.llc)
    broker = Broker("b0", catalog)
    broker.register_server_handle("server_0", server.execute_partial)

    schema = Schema("t", [dimension("s"), metric("m", DataType.DOUBLE)])
    ctrl.add_schema(schema)
    cfg = TableConfig("t", replication=1)
    ctrl.add_table(cfg)
    seg = SegmentBuilder(schema).build(
        {"s": ["a", "b", "a"], "m": np.array([1.0, 2.0, 3.0])},
        str(tmp_path / "b"), "t_0")
    ctrl.upload_segment(cfg.table_name_with_type, seg)
    assert wait_until(lambda: server.segments_served(
        cfg.table_name_with_type) == ["t_0"], timeout=15)
    res = broker.handle_query("SELECT s, SUM(m) FROM t GROUP BY s ORDER BY s")
    assert res.rows == [["a", 4.0], ["b", 2.0]]
    # the committed tar genuinely lives in the object store
    assert any(k.startswith("deepstore/t_OFFLINE/") for k in stub.objects)
    ctrl.delete_segment(cfg.table_name_with_type, "t_0", permanent=True)
    assert wait_until(lambda: not any(
        k.startswith("deepstore/t_OFFLINE/") and k.endswith(".tar.gz")
        for k in stub.objects), timeout=10)


def test_leadership_lease_on_s3(stub):
    """The controller leadership lease (CAS-by-fencing blob) works over the
    S3 wire exactly as over the local FS."""
    from pinot_tpu.cluster.leadership import LeaderElection
    fs = create_fs(stub.spec("ha"))
    a = LeaderElection(fs, "c1", lease_ttl_s=0.4, settle_s=0.0)
    b = LeaderElection(fs, "c2", lease_ttl_s=0.4, settle_s=0.0)
    assert a.try_acquire()
    assert not b.try_acquire()          # lease held
    assert a.renew()
    time.sleep(0.6)                     # let it expire without renewal
    assert b.try_acquire()              # takeover after expiry
    assert not a.renew()                # deposed leader cannot renew
    b.release()
    assert a.try_acquire()


def test_process_cluster_on_s3_with_outage_heals(tmp_path):
    """Full chaos flow over the s3 scheme: a ProcessCluster whose controller
    deep store is the S3 stub commits realtime segments through it; an S3
    outage mid-stream still commits (peer scheme) and converges; after the
    stub recovers, a validation round heals the segment into S3."""
    from pinot_tpu.cluster.http_service import post_json
    from pinot_tpu.cluster.process import ProcessCluster
    from pinot_tpu.ingest.kafkalite import LogBrokerClient, LogBrokerServer

    stub = S3StubServer(bucket="pinot", access_key="AKIATEST",
                        secret_key="sekrit")
    srv = LogBrokerServer()
    try:
        client = LogBrokerClient(srv.bootstrap)
        client.create_topic("s3t", 1)
        cfg_path = tmp_path / "cluster.conf"
        cfg_path.write_text(
            f"controller.deepstore={stub.spec('deepstore')}\n")
        schema = Schema("s3t", [
            dimension("u", DataType.STRING), metric("v", DataType.LONG),
            date_time("ts", DataType.LONG)])
        with ProcessCluster(num_servers=2, work_dir=str(tmp_path),
                            config_path=str(cfg_path)) as cluster:
            cluster.controller.add_schema(schema)
            cfg = TableConfig(
                "s3t", table_type=TableType.REALTIME, time_column="ts",
                replication=2,
                stream=StreamConfig(stream_type="kafkalite", topic="s3t",
                                    properties={"bootstrap": srv.bootstrap},
                                    flush_threshold_rows=25))
            cluster.controller.add_table(cfg, num_partitions=1)
            table = cfg.table_name_with_type

            def count():
                rows = cluster.query(
                    "SELECT COUNT(*) FROM s3t")["resultTable"]["rows"]
                return rows[0][0] if rows else 0

            for i in range(30):
                client.produce("s3t", json.dumps(
                    {"u": f"u{i % 3}", "v": i, "ts": 1700000000000 + i}))
            assert wait_until(lambda: count() == 30, timeout=30)

            def done_segments():
                metas = cluster.controller.segments_meta(table)["segments"]
                return {n: m for n, m in metas.items()
                        if m.get("status") == "DONE"}
            assert wait_until(lambda: len(done_segments()) >= 1, timeout=40)
            # the healthy commit really went to S3
            assert any(k.endswith(".tar.gz") for k in stub.objects)

            # OUTAGE: commits keep landing via the peer scheme
            stub.outage = True
            try:
                for i in range(30, 60):
                    client.produce("s3t", json.dumps(
                        {"u": f"u{i % 3}", "v": i, "ts": 1700000000000 + i}))
                assert wait_until(
                    lambda: any(str(m.get("download_path", "")).startswith(
                        "peer://") for m in done_segments().values()),
                    timeout=40), "commit must survive the S3 outage"
                assert wait_until(lambda: count() == 60, timeout=30)
                assert wait_until(lambda: cluster.controller.table_status(
                    table)["converged"], timeout=30)
            finally:
                stub.outage = False

            # recovery: validation re-uploads peer segments into S3
            peer_segs = [n for n, m in done_segments().items()
                         if str(m.get("download_path", "")
                                ).startswith("peer://")]
            healed = post_json(f"{cluster.controller_url}/validate", {})
            assert set(peer_segs) <= set(healed.get("healed", [])), healed
            metas = cluster.controller.segments_meta(table)["segments"]
            for n in peer_segs:
                assert not metas[n]["download_path"].startswith("peer://")
    finally:
        srv.stop()
        stub.stop()


def test_list_pagination_and_encoded_keys(stub):
    """Review round: the client follows IsTruncated/NextContinuationToken
    across pages (real S3 caps a page at 1000), keys needing percent-encoding
    sign correctly (no double-encoding), and a recursive delete mid-outage
    raises instead of silently succeeding."""
    fs = create_fs(stub.spec("pg") + "&pageSize=7")
    for i in range(25):
        fs.put_bytes(b"x", f"d/k{i:03d}")
    fs.put_bytes(b"y", "d/sub/inner")
    assert len(fs._list_keys("pg/d/")) == 26
    names = fs.listdir("d")
    assert len(names) == 26 and "sub" in names and "k000" in names
    # percent-encoded key: space + colon survive sign + roundtrip
    fs.put_bytes(b"enc", "d/seg a:b.tar.gz")
    assert fs.get_bytes("d/seg a:b.tar.gz") == b"enc"
    # recursive delete across pages removes everything
    fs.delete("d")
    assert not fs.exists("d")
    # mid-outage delete must raise, not silently succeed
    fs.put_bytes(b"x", "e/k")
    stub.outage = True
    try:
        with pytest.raises(S3Error):
            fs.delete("e")
    finally:
        stub.outage = False
    assert fs.exists("e/k")
