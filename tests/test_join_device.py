"""Device hash-join fast path (PR 17): differential suite vs the host oracle.

Every test forces the device path on (`configure_device_join(min_rows=0)`) and
compares `hash_join` — the scatter/sort-merge kernels plus host verification —
against `hash_join_host`, the numpy factorize oracle, as exact row multisets.
Covers all six join types, null keys, dtype-promoted keys, the MV/mixed-object
fallback, zipf probe skew (`joinSkewPct`), partitioned-exchange widths 1 and 8,
the capacity-pinned admission degradation (`joinServedHostTier`), the JoinSpec
JSON roundtrip for SEMI/ANTI, and `WHERE x IN (SELECT ...)` lowering against a
sqlite oracle.
"""

import sqlite3

import numpy as np
import pytest

from pinot_tpu.multistage import execute_multistage
from pinot_tpu.multistage.planner import JoinSpec
from pinot_tpu.multistage.runtime import (_block_rows, _DEVICE_JOIN,
                                          configure_device_join, hash_join,
                                          hash_join_host, make_segment_scan,
                                          spec_from_json, spec_to_json)
from pinot_tpu.multistage.shuffle import _partition_join_input
from pinot_tpu.query import stats as qstats
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.segment.reader import load_segment
from pinot_tpu.segment.writer import SegmentBuilder

JOIN_TYPES = ("inner", "left", "right", "full", "semi", "anti")


@pytest.fixture(autouse=True)
def _force_device_join():
    saved = dict(_DEVICE_JOIN)
    configure_device_join(enabled=True, min_rows=0)
    yield
    configure_device_join(**saved)


def _rows_of(block):
    """Canonical sorted row-tuples of a Block: None/NaN collapse to markers,
    numerics compare as rounded floats (int vs float64 promotion must not
    fail equality), everything else by repr."""
    cols = sorted(block)
    rows = []
    for i in range(_block_rows(block)):
        row = []
        for c in cols:
            v = block[c][i]
            if v is None:
                row.append("<null>")
            elif isinstance(v, (float, np.floating)) and np.isnan(v):
                row.append("<nan>")
            elif isinstance(v, (int, float, np.integer, np.floating)):
                row.append(round(float(v), 9))
            else:
                row.append(repr(v))
        rows.append(tuple(row))
    return sorted(rows, key=repr)


def _assert_device_matches_host(left, right, spec, expect_device=True):
    with qstats.collect_stats() as st:
        dev = hash_join(left, right, spec)
    host = hash_join_host(left, right, spec)
    assert _rows_of(dev) == _rows_of(host), spec
    ran_device = (qstats.JOIN_BUILD_MS in st.counters
                  or qstats.JOIN_PROBE_MS in st.counters)
    assert ran_device == expect_device, dict(st.counters)
    return dev


def _int_blocks(rng, n=1500, m=400, card=120):
    left = {"lk": rng.integers(0, card, n).astype(np.int64),
            "v": np.round(rng.uniform(0, 100, n), 3)}
    right = {"rk": rng.integers(0, card, m).astype(np.int64),  # dup build keys
             "w": np.round(rng.uniform(0, 100, m), 3)}
    return left, right


@pytest.mark.parametrize("how", JOIN_TYPES)
def test_device_vs_host_int_keys(how):
    rng = np.random.default_rng(17)
    left, right = _int_blocks(rng)
    spec = JoinSpec(right_alias="r", join_type=how,
                    left_keys=["lk"], right_keys=["rk"])
    _assert_device_matches_host(left, right, spec)


@pytest.mark.parametrize("how", JOIN_TYPES)
def test_device_vs_host_string_keys(how):
    rng = np.random.default_rng(23)
    univ = np.array([f"k{i}" for i in range(90)], dtype=object)
    left = {"lk": univ[rng.integers(0, 90, 1200)],
            "v": rng.integers(0, 50, 1200).astype(np.int32)}
    right = {"rk": univ[rng.integers(0, 90, 300)],
             "w": np.round(rng.uniform(0, 10, 300), 3)}
    spec = JoinSpec(right_alias="r", join_type=how,
                    left_keys=["lk"], right_keys=["rk"])
    _assert_device_matches_host(left, right, spec)


@pytest.mark.parametrize("how", JOIN_TYPES)
def test_device_vs_host_null_keys(how):
    """NaN keys never match: left/full/anti keep them null-extended (anti:
    kept outright — NOT EXISTS semantics), inner/semi/right drop them."""
    rng = np.random.default_rng(31)
    lk = rng.integers(0, 60, 900).astype(np.float64)
    lk[rng.random(900) < 0.15] = np.nan
    rk = rng.integers(0, 60, 250).astype(np.float64)
    rk[rng.random(250) < 0.15] = np.nan
    left = {"lk": lk, "v": rng.integers(0, 9, 900).astype(np.int64)}
    right = {"rk": rk, "w": rng.integers(0, 9, 250).astype(np.int64)}
    spec = JoinSpec(right_alias="r", join_type=how,
                    left_keys=["lk"], right_keys=["rk"])
    _assert_device_matches_host(left, right, spec)


@pytest.mark.parametrize("how", ("inner", "left", "semi", "anti"))
def test_device_vs_host_dtype_promoted_keys(how):
    """int32 probe keys joining float64 build keys (an upstream outer join
    promoted one side): int 3 must meet double 3.0 on both paths."""
    rng = np.random.default_rng(41)
    left = {"lk": rng.integers(0, 80, 1000).astype(np.int32),
            "v": np.round(rng.uniform(0, 5, 1000), 3)}
    right = {"rk": rng.integers(0, 80, 200).astype(np.float64),
             "w": np.round(rng.uniform(0, 5, 200), 3)}
    spec = JoinSpec(right_alias="r", join_type=how,
                    left_keys=["lk"], right_keys=["rk"])
    _assert_device_matches_host(left, right, spec)


def test_mv_and_mixed_object_keys_fall_back_to_host():
    """Non-scalar (MV tuple cells) and mixed-type object key columns are not
    vectorizable: `hash_join` must route to the host oracle — same rows, no
    device kernel launches."""
    rng = np.random.default_rng(47)
    tuples = np.empty(600, dtype=object)
    rtuples = np.empty(90, dtype=object)
    for i in range(600):
        tuples[i] = ("a", int(rng.integers(0, 30)))
    for i in range(90):
        rtuples[i] = ("a", int(rng.integers(0, 30)))
    left = {"lk": tuples, "v": rng.integers(0, 9, 600).astype(np.int64)}
    right = {"rk": rtuples, "w": rng.integers(0, 9, 90).astype(np.int64)}
    spec = JoinSpec(right_alias="r", join_type="inner",
                    left_keys=["lk"], right_keys=["rk"])
    _assert_device_matches_host(left, right, spec, expect_device=False)

    mixed = np.array([("s%d" % i) if i % 2 else i for i in range(400)],
                     dtype=object)
    left = {"lk": mixed, "v": np.arange(400, dtype=np.int64)}
    right = {"rk": mixed[:100].copy(), "w": np.arange(100, dtype=np.int64)}
    _assert_device_matches_host(left, right, spec, expect_device=False)


def test_zipf_probe_skew_records_join_skew_pct():
    """A zipf-heavy probe side must light up the kernels' fold-bucket
    histogram (`joinSkewPct` > 0) while the joined rows stay oracle-exact."""
    rng = np.random.default_rng(53)
    card = 64
    p = np.arange(1, card + 1, dtype=np.float64) ** -1.6
    p /= p.sum()
    left = {"lk": rng.choice(card, 6000, p=p).astype(np.int64),
            "v": rng.integers(0, 9, 6000).astype(np.int64)}
    right = {"rk": np.arange(card, dtype=np.int64),
             "w": rng.integers(0, 9, card).astype(np.int64)}
    spec = JoinSpec(right_alias="r", join_type="inner",
                    left_keys=["lk"], right_keys=["rk"])
    with qstats.collect_stats() as st:
        dev = hash_join(left, right, spec)
    assert st.counters.get(qstats.JOIN_SKEW_PCT, 0.0) > 0.0, \
        dict(st.counters)
    assert _rows_of(dev) == _rows_of(hash_join_host(left, right, spec))


@pytest.mark.parametrize("p", (1, 8))
def test_partitioned_exchange_widths(p):
    """Hash-partition both sides across `p` workers (the mailbox-exchange
    shape), device-join every co-partition with its staged codes, and the
    union must equal the whole-block host join — width 1 and width 8."""
    rng = np.random.default_rng(59 + p)
    left, right = _int_blocks(rng, n=2200, m=500, card=150)
    spec = JoinSpec(right_alias="r", join_type="inner",
                    left_keys=["lk"], right_keys=["rk"])
    lparts, _ = _partition_join_input(left, ["lk"], p, "partitioned", "L")
    rparts, _ = _partition_join_input(right, ["rk"], p, "partitioned", "R")
    got = []
    for lp, rp in zip(lparts, rparts):
        j = hash_join(lp.block, rp.block, spec,
                      lcodes=lp.codes, rcodes=rp.codes)
        got.extend(_rows_of(j))
    assert sorted(got, key=repr) == _rows_of(
        hash_join_host(left, right, spec))


def test_broadcast_exchange_equals_partitioned():
    """Broadcast (replicated build, strip-split probe) must produce the same
    multiset as the partitioned exchange on the same inputs."""
    rng = np.random.default_rng(67)
    left, right = _int_blocks(rng, n=1800, m=120, card=80)
    spec = JoinSpec(right_alias="r", join_type="inner",
                    left_keys=["lk"], right_keys=["rk"])
    got = []
    lparts, _ = _partition_join_input(left, ["lk"], 4, "broadcast", "L")
    rparts, _ = _partition_join_input(right, ["rk"], 4, "broadcast", "R")
    for lp, rp in zip(lparts, rparts):
        j = hash_join(lp.block, rp.block, spec,
                      lcodes=lp.codes, rcodes=rp.codes)
        got.extend(_rows_of(j))
    assert sorted(got, key=repr) == _rows_of(
        hash_join_host(left, right, spec))


def test_capacity_pinned_admission_degrades_to_host(monkeypatch):
    """With HBM capacity pinned to a few hundred bytes the admission gate
    must price the join off the device (`joinServedHostTier`), serve it from
    the host oracle, and stay deterministic across runs."""
    from pinot_tpu.utils.memledger import reset_ledger

    monkeypatch.setenv("PINOT_TPU_HBM_CAPACITY_BYTES", "1000")
    reset_ledger()
    try:
        rng = np.random.default_rng(71)
        left, right = _int_blocks(rng, n=3000, m=600, card=40)  # dup-heavy
        spec = JoinSpec(right_alias="r", join_type="inner",
                        left_keys=["lk"], right_keys=["rk"])
        with qstats.collect_stats() as st:
            out1 = hash_join(left, right, spec)
        assert st.counters.get(qstats.JOIN_SERVED_HOST_TIER, 0) >= 1, \
            dict(st.counters)
        out2 = hash_join(left, right, spec)
        assert _rows_of(out1) == _rows_of(out2)        # same-seed determinism
        assert _rows_of(out1) == _rows_of(hash_join_host(left, right, spec))
    finally:
        monkeypatch.delenv("PINOT_TPU_HBM_CAPACITY_BYTES")
        reset_ledger()


@pytest.mark.parametrize("how", ("semi", "anti"))
def test_join_spec_json_roundtrip_semi_anti(how):
    spec = JoinSpec(right_alias="__in0", join_type=how,
                    left_keys=["o.cust_id"], right_keys=["__in0.cust_id"])
    rt = spec_from_json(spec_to_json(spec))
    assert (rt.right_alias, rt.join_type, rt.left_keys, rt.right_keys,
            rt.residual) == ("__in0", how, ["o.cust_id"],
                             ["__in0.cust_id"], None)


# -- IN (SELECT ...) lowering vs sqlite --------------------------------------

ORDERS_SCHEMA = Schema("orders", [
    dimension("cust_id"), metric("qty", DataType.INT),
    metric("amount", DataType.DOUBLE)])
CUSTS_SCHEMA = Schema("custs", [
    dimension("cust_id"), dimension("region"), metric("tier", DataType.INT)])


@pytest.fixture(scope="module")
def subquery_env(tmp_path_factory):
    rng = np.random.default_rng(83)
    n, m = 1200, 60
    orders = {"cust_id": [f"c{i}" for i in rng.integers(0, 80, n)],
              "qty": rng.integers(1, 20, n).astype(np.int32),
              "amount": np.round(rng.uniform(1, 200, n), 2)}
    custs = {"cust_id": [f"c{i}" for i in range(m)],  # c60..c79 dangle
             "region": [["east", "west"][i % 2] for i in range(m)],
             "tier": rng.integers(1, 4, m).astype(np.int32)}
    tmp = tmp_path_factory.mktemp("insub")
    o_seg = load_segment(SegmentBuilder(ORDERS_SCHEMA).build(
        dict(orders), str(tmp), "o_0"))
    c_seg = load_segment(SegmentBuilder(CUSTS_SCHEMA).build(
        dict(custs), str(tmp), "c_0"))
    scan = make_segment_scan({"orders": [o_seg], "custs": [c_seg]})
    schema_for = {"orders": ORDERS_SCHEMA, "custs": CUSTS_SCHEMA}.get
    db = sqlite3.connect(":memory:")
    db.execute("CREATE TABLE orders (cust_id TEXT, qty INTEGER, amount REAL)")
    db.execute("CREATE TABLE custs (cust_id TEXT, region TEXT, tier INTEGER)")
    db.executemany("INSERT INTO orders VALUES (?,?,?)",
                   list(zip(orders["cust_id"], orders["qty"].tolist(),
                            orders["amount"].tolist())))
    db.executemany("INSERT INTO custs VALUES (?,?,?)",
                   list(zip(custs["cust_id"], custs["region"],
                            custs["tier"].tolist())))
    return scan, schema_for, db


@pytest.mark.parametrize("neg", (False, True))
def test_in_subquery_lowers_to_semi_anti_vs_sqlite(subquery_env, neg):
    scan, schema_for, db = subquery_env
    op = "NOT IN" if neg else "IN"
    sql = (f"SELECT COUNT(*), SUM(amount) FROM orders WHERE qty > 3 AND "
           f"cust_id {op} (SELECT cust_id FROM custs WHERE tier = 2) LIMIT 5")
    want = db.execute(sql.replace(" LIMIT 5", "")).fetchone()
    got = execute_multistage(sql, scan, schema_for).rows[0]
    assert got[0] == want[0]
    assert abs(got[1] - (want[1] or 0.0)) <= 1e-6 * max(1.0, abs(want[1] or 0))


def test_in_subquery_grouped_vs_sqlite(subquery_env):
    scan, schema_for, db = subquery_env
    sql = ("SELECT cust_id, COUNT(*) FROM orders WHERE cust_id IN "
           "(SELECT cust_id FROM custs WHERE region = 'east') "
           "GROUP BY cust_id LIMIT 1000")
    want = sorted(db.execute(sql.replace(" LIMIT 1000", "")).fetchall())
    got = sorted((r[0], r[1]) for r in
                 execute_multistage(sql, scan, schema_for).rows)
    assert got == want
