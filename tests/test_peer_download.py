"""Peer segment download: commit survives a deep-store outage (peer scheme),
replicas and movers fetch from serving peers, and the validation round heals
the deep store once it recovers.

Reference: `PeerServerSegmentFinder.java` + PeerSchemeSplitSegmentCommitter +
RealtimeSegmentValidationManager.uploadToDeepStoreIfMissing.
"""

import json
import os
import time

import numpy as np

from pinot_tpu.cluster.http_service import get_json, post_json
from pinot_tpu.cluster.process import ProcessCluster
from pinot_tpu.ingest.kafkalite import LogBrokerClient, LogBrokerServer
from pinot_tpu.schema import DataType, Schema, date_time, dimension, metric
from pinot_tpu.table import StreamConfig, TableConfig, TableType

from conftest import wait_until


def _break_deepstore(work_dir: str) -> None:
    """Make every deep-store write/read fail: replace the root dir with a
    regular file (works even as root, unlike permission bits)."""
    root = os.path.join(work_dir, "deepstore")
    os.rename(root, root + ".parked")
    with open(root, "w") as f:
        f.write("outage")


def _restore_deepstore(work_dir: str) -> None:
    root = os.path.join(work_dir, "deepstore")
    os.remove(root)
    os.rename(root + ".parked", root)


def test_commit_and_convergence_survive_deepstore_outage(tmp_path):
    schema = Schema("pv", [
        dimension("u", DataType.STRING),
        metric("v", DataType.LONG),
        date_time("ts", DataType.LONG),
    ])
    srv = LogBrokerServer()
    try:
        client = LogBrokerClient(srv.bootstrap)
        client.create_topic("pv_t", 1)
        with ProcessCluster(num_servers=2, work_dir=str(tmp_path)) as cluster:
            cluster.controller.add_schema(schema)
            cfg = TableConfig(
                "pv", table_type=TableType.REALTIME, time_column="ts",
                replication=2,
                stream=StreamConfig(stream_type="kafkalite", topic="pv_t",
                                    properties={"bootstrap": srv.bootstrap},
                                    flush_threshold_rows=30))
            cluster.controller.add_table(cfg, num_partitions=1)
            table = cfg.table_name_with_type

            def count():
                rows = cluster.query(
                    "SELECT COUNT(*) FROM pv")["resultTable"]["rows"]
                return rows[0][0] if rows else 0

            # a first healthy flush proves the normal path, then the OUTAGE
            for i in range(10):
                client.produce("pv_t", json.dumps(
                    {"u": f"u{i % 3}", "v": i, "ts": 1700000000000 + i}))
            assert wait_until(lambda: count() == 10, timeout=60)

            _break_deepstore(str(tmp_path))
            try:
                for i in range(10, 40):
                    client.produce("pv_t", json.dumps(
                        {"u": f"u{i % 3}", "v": i, "ts": 1700000000000 + i}))

                # the segment COMMITS despite the dead deep store — under the
                # peer download scheme
                def done_segments():
                    metas = cluster.controller.segments_meta(table)["segments"]
                    return {n: m for n, m in metas.items()
                            if m.get("status") == "DONE"}
                assert wait_until(lambda: len(done_segments()) >= 1,
                                  timeout=90), "commit must survive the outage"
                peer_segs = [n for n, m in done_segments().items()
                             if str(m.get("download_path", "")
                                    ).startswith("peer://")]
                assert peer_segs, done_segments()
                assert wait_until(lambda: count() == 40, timeout=60)

                # EV converges: BOTH replicas serve the committed segment
                def converged():
                    return cluster.controller.table_status(table)["converged"]
                assert wait_until(converged, timeout=60)

                # a server that must DOWNLOAD the segment (post-restart, local
                # data wiped) fetches it from a peer, deep store still dead
                import shutil
                victim = peer_segs[0]
                shutil.rmtree(os.path.join(str(tmp_path), "server_1", table),
                              ignore_errors=True)
                cluster.restart_server("server_1")
                assert wait_until(converged, timeout=90), \
                    "restarted replica must converge via peer download"
                assert wait_until(lambda: count() == 40, timeout=60)
            finally:
                _restore_deepstore(str(tmp_path))

            # deep store is back: one validation round re-uploads the
            # peer-scheme segment and flips its path to the durable URI
            healed = post_json(f"{cluster.controller_url}/validate", {})
            assert set(peer_segs) <= set(healed.get("healed", [])), healed
            metas = cluster.controller.segments_meta(table)["segments"]
            for n in peer_segs:
                path = metas[n]["download_path"]
                assert not path.startswith("peer://")
                assert os.path.exists(
                    os.path.join(str(tmp_path), "deepstore", path))
    finally:
        srv.stop()
