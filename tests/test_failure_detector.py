"""Failure detector tests: backoff re-probing returns blipped servers to
routing (reference: BaseExponentialBackoffRetryFailureDetector).
"""

import numpy as np
import pytest

from pinot_tpu.cluster.broker import FailureDetector
from pinot_tpu.cluster import QuickCluster
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.table import TableConfig


class FakeRouting:
    def __init__(self):
        self.healthy = []

    def mark_server_healthy(self, s):
        self.healthy.append(s)


def test_backoff_schedule_and_recovery():
    routing = FakeRouting()
    fd = FailureDetector(routing, initial_interval_s=1.0, backoff_factor=2.0,
                         max_interval_s=8.0)
    state = {"up": False}
    fd.register_probe("s1", lambda: state["up"])
    fd.notify_unhealthy("s1")

    t0 = 1000.0
    fd._pending["s1"] = (t0 + 1.0, 1.0)   # pin the schedule for determinism
    fd.tick(t0 + 0.5)                      # not due yet
    assert routing.healthy == []
    fd.tick(t0 + 1.0)                      # due, probe fails -> backoff 2s
    assert fd._pending["s1"][1] == 2.0
    fd.tick(t0 + 3.0)                      # fails -> 4s
    fd.tick(t0 + 7.0)                      # fails -> 8s
    fd.tick(t0 + 15.0)                     # fails -> capped at 8s
    assert fd._pending["s1"][1] == 8.0
    state["up"] = True
    fd.tick(t0 + 23.0)                     # probe succeeds
    assert routing.healthy == ["s1"]
    assert "s1" not in fd._pending


def test_no_probe_means_manual_recovery_only():
    routing = FakeRouting()
    fd = FailureDetector(routing)
    fd.notify_unhealthy("mystery")         # no registered probe: not tracked
    fd.tick(1e12)
    assert routing.healthy == [] and not fd._pending


def test_broker_recovers_blipped_server(tmp_path):
    """End-to-end: a failing server drops out of routing after a bad query and
    returns automatically once its probe passes."""
    cluster = QuickCluster(num_servers=2, work_dir=str(tmp_path))
    schema = Schema("t", [dimension("s"), metric("v", DataType.DOUBLE)])
    cfg = cluster.create_table(schema, TableConfig("t", replication=2))
    cluster.ingest_columns(cfg, {"s": ["a", "b"], "v": np.array([1.0, 2.0])})

    broken = {"on": True}
    real = cluster.servers[0].execute_partial

    def flaky(*args, **kw):
        if broken["on"]:
            raise ConnectionError("transport blip")
        return real(*args, **kw)
    cluster.broker.register_server_handle(
        "server_0", flaky, probe=lambda: not broken["on"])

    cluster.query("SELECT s, COUNT(*) FROM t GROUP BY s LIMIT 5")
    assert "server_0" in cluster.broker.routing._unhealthy
    assert "server_0" in cluster.broker.failure_detector._pending
    # with server_0 excluded, the healthy replica answers everything
    res = cluster.query("SELECT s, COUNT(*) FROM t GROUP BY s LIMIT 5")
    assert sum(r[1] for r in res.rows) == 2

    # probe keeps failing -> still excluded
    cluster.broker.failure_detector.tick(now=1e12)
    assert "server_0" in cluster.broker.routing._unhealthy

    # server recovers -> next probe re-admits it
    broken["on"] = False
    cluster.broker.failure_detector.tick(now=2e12)
    assert "server_0" not in cluster.broker.routing._unhealthy
    res = cluster.query("SELECT s, COUNT(*) FROM t GROUP BY s LIMIT 5")
    assert sum(r[1] for r in res.rows) == 2
    assert res.stats["numServersResponded"] == res.stats["numServersQueried"]
