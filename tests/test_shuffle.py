"""Server↔server mailbox shuffle tests: the P2P multistage data plane.

Coverage mirrors the reference's mailbox/exchange tests
(`pinot-query-runtime/src/test/.../MailboxSendOperatorTest.java`,
`GrpcMailboxServiceTest.java`, `QueryRunnerTest`): partition routing is
deterministic across processes, bounded buffering backpressures, cancellation
unwinds cleanly, join results through the P2P path match the sqlite oracle,
single-table GROUP BY distributes across workers, and the broker's data-plane
memory stays flat (enforced by a cap the funnel path trips and the shuffle
path never touches).
"""

import sqlite3
import threading
import time

import numpy as np
import pytest

from pinot_tpu.cluster.broker import Broker
from pinot_tpu.cluster.catalog import Catalog
from pinot_tpu.cluster.controller import Controller
from pinot_tpu.cluster.deepstore import LocalDeepStore
from pinot_tpu.cluster.process import BrokerClient, ControllerClient
from pinot_tpu.cluster.remote import ControllerDeepStore, RemoteCatalog
from pinot_tpu.cluster.server import ServerNode
from pinot_tpu.cluster.services import (BrokerService, ControllerService,
                                        ServerService)
from pinot_tpu.multistage.shuffle import (MailboxCancelled, REGISTRY,
                                          SegmentResult, StageCtx, _Mailbox,
                                          partition_block_stable,
                                          partition_groups_stable,
                                          stable_hash_codes, stable_hash_key,
                                          trim_group_result)
from pinot_tpu.query.aggregates import make_agg
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.segment.writer import SegmentBuilder
from pinot_tpu.sql.ast import Function, Identifier
from pinot_tpu.table import TableConfig

from conftest import wait_until
from test_differential import _rows_match, _sorted_rows

# ---------------------------------------------------------------------------
# unit: stable partition routing
# ---------------------------------------------------------------------------

def test_stable_hash_routes_equal_keys_identically():
    """Two independently-built blocks (as two leaf servers would build them)
    must route equal keys to the same partition — Python's randomized hash()
    would not."""
    a = {"k": np.array(["x", "y", "z", "x"], dtype=object)}
    b = {"k": np.array(["z", "x", "q"], dtype=object)}
    pa = (stable_hash_codes(a, ["k"]) % np.uint64(8)).tolist()
    pb = (stable_hash_codes(b, ["k"]) % np.uint64(8)).tolist()
    assert pa[0] == pa[3] == pb[1]      # "x" always lands together
    assert pa[2] == pb[0]               # "z" too


def test_stable_hash_numeric_dtype_canonicalization():
    """int 3 and double 3.0 must co-partition (outer joins can promote one
    side to float)."""
    ints = {"k": np.array([3, 7, 0], dtype=np.int64)}
    flts = {"k": np.array([3.0, 7.0, -0.0], dtype=np.float64)}
    pi = (stable_hash_codes(ints, ["k"]) % np.uint64(16)).tolist()
    pf = (stable_hash_codes(flts, ["k"]) % np.uint64(16)).tolist()
    assert pi == pf


def test_partition_block_stable_partitions_cover_exactly():
    rng = np.random.default_rng(7)
    block = {"k": np.array([f"u{i}" for i in rng.integers(0, 50, 300)],
                           dtype=object),
             "v": rng.uniform(0, 1, 300)}
    parts = partition_block_stable(block, ["k"], 8)
    assert sum(len(p["v"]) for p in parts) == 300
    # the same key never appears in two partitions
    seen = {}
    for pi, p in enumerate(parts):
        for k in p["k"]:
            assert seen.setdefault(k, pi) == pi


def test_partition_groups_stable_disjoint_union():
    res = SegmentResult("groups")
    res.groups = {(f"k{i}", i % 3): [float(i)] for i in range(100)}
    res.num_docs_scanned = 1234
    parts = partition_groups_stable(res, 4)
    assert sum(len(p.groups) for p in parts) == 100
    assert sum(p.num_docs_scanned for p in parts) == 1234
    merged = {}
    for p in parts:
        for k, v in p.groups.items():
            assert k not in merged
            merged[k] = v
    assert merged == res.groups
    # same key -> same partition on a rebuild (cross-process determinism)
    again = partition_groups_stable(res, 4)
    for p1, p2 in zip(parts, again):
        assert set(p1.groups) == set(p2.groups)
    assert stable_hash_key(("a", 1)) == stable_hash_key(("a", 1))


# ---------------------------------------------------------------------------
# unit: worker-side trim (HAVING + top-k on a disjoint key range)
# ---------------------------------------------------------------------------

def _sum_agg():
    return Function("sum", (Identifier("v"),))


def test_trim_group_result_having_and_topk():
    call = _sum_agg()
    ctx = StageCtx(select_items=[(Identifier("g"), "g"), (call, "s")],
                   group_by=[Identifier("g")], aggregations=[call],
                   having=Function("gt", (call, __import__(
                       "pinot_tpu.sql.ast", fromlist=["Literal"]).Literal(10.0))),
                   order_by=[__import__(
                       "pinot_tpu.sql.ast", fromlist=["OrderByItem"]
                   ).OrderByItem(call, desc=True)],
                   limit=3, offset=0)
    aggs = [make_agg(call)]
    merged = SegmentResult("groups")
    # states for SUM are plain floats
    merged.groups = {(f"g{i}",): [float(i)] for i in range(30)}
    out = trim_group_result(ctx, merged, aggs)
    # HAVING sum > 10 keeps g11..g29; top-3 by sum desc = g29,g28,g27
    assert set(out.groups) == {("g29",), ("g28",), ("g27",)}
    # states are preserved un-finalized (still mergeable)
    assert out.groups[("g29",)] == [29.0]


def test_trim_group_result_no_trim_needed_is_identity():
    call = _sum_agg()
    ctx = StageCtx(select_items=[(Identifier("g"), "g"), (call, "s")],
                   group_by=[Identifier("g")], aggregations=[call])
    merged = SegmentResult("groups")
    merged.groups = {("a",): [1.0], ("b",): [2.0]}
    assert trim_group_result(ctx, merged, [make_agg(call)]) is merged


# ---------------------------------------------------------------------------
# unit: mailbox semantics (bounded buffering, cancellation)
# ---------------------------------------------------------------------------

def test_mailbox_backpressure_blocks_then_drains():
    box = _Mailbox(window=2)
    box.put(("block", 1))
    box.put(("block", 2))
    t0 = time.time()
    done = []

    def producer():
        box.put(("block", 3), timeout_s=10)   # blocks until a consumer pops
        done.append(time.time() - t0)

    t = threading.Thread(target=producer)
    t.start()
    time.sleep(0.3)
    assert not done              # still blocked on the full window
    assert box.get() == ("block", 1)
    t.join(timeout=5)
    assert done and done[0] >= 0.25


def test_mailbox_cancel_wakes_blocked_producer_and_consumer():
    box = _Mailbox(window=1)
    box.put(("block", 1))
    errs = []

    def producer():
        try:
            box.put(("block", 2), timeout_s=30)
        except MailboxCancelled:
            errs.append("producer")

    t = threading.Thread(target=producer)
    t.start()
    time.sleep(0.1)
    box.cancelled.set()
    t.join(timeout=5)
    assert errs == ["producer"]
    with pytest.raises(MailboxCancelled):
        box.get(timeout_s=30)


def test_registry_cancel_tombstones_new_opens():
    REGISTRY.cancel_query("qdead")
    with pytest.raises(MailboxCancelled):
        REGISTRY.open("qdead", "join0.L.0")
    REGISTRY._cancelled.pop("qdead", None)  # don't leak into other tests


# ---------------------------------------------------------------------------
# integration: full P2P shuffle over an HTTP cluster
# ---------------------------------------------------------------------------

RNG = np.random.default_rng(42)
N_ORDERS = 2000

ORDERS = {
    "cust_id": [f"c{i}" for i in RNG.integers(0, 100, N_ORDERS)],
    "qty": RNG.integers(1, 20, N_ORDERS).astype(np.int32),
    "amount": np.round(RNG.uniform(1, 500, N_ORDERS), 2),
}
CUSTS = {
    "cust_id": [f"c{i}" for i in range(80)],
    "region": [["east", "west", "north"][i % 3] for i in range(80)],
    "tier": RNG.integers(1, 4, 80).astype(np.int32),
}
REGIONS = {
    "region": ["east", "west", "north"],
    "zone": ["Z1", "Z2", "Z1"],
}

ORDERS_SCHEMA = Schema("orders", [
    dimension("cust_id"), metric("qty", DataType.INT),
    metric("amount", DataType.DOUBLE)])
CUSTS_SCHEMA = Schema("custs", [
    dimension("cust_id"), dimension("region"), metric("tier", DataType.INT)])
REGIONS_SCHEMA = Schema("regions", [dimension("region"), dimension("zone")])


def _slice(cols, lo, hi):
    return {k: (v[lo:hi] if isinstance(v, np.ndarray) else list(v[lo:hi]))
            for k, v in cols.items()}


@pytest.fixture(scope="module")
def shuffle_cluster(tmp_path_factory):
    """Controller + 2 HTTP servers + broker; orders split into 4 segments so
    both servers hold data and every join crosses the wire."""
    tmp = tmp_path_factory.mktemp("shuffle")
    catalog = Catalog()
    deepstore = LocalDeepStore(str(tmp / "deepstore"))
    controller = Controller("controller_0", catalog, deepstore, str(tmp / "c"))
    csvc = ControllerService(controller)
    services = [csvc]
    catalogs = []
    nodes = []
    try:
        for i in range(2):
            rc = RemoteCatalog(csvc.url, poll_timeout_s=1.0)
            catalogs.append(rc)
            node = ServerNode(f"server_{i}", rc, ControllerDeepStore(csvc.url),
                              str(tmp / f"s{i}"))
            services.append(ServerService(node))
            nodes.append(node)
        brc = RemoteCatalog(csvc.url, poll_timeout_s=1.0)
        catalogs.append(brc)
        broker = Broker("broker_0", brc)
        bsvc = BrokerService(broker)
        services.append(bsvc)

        cc = ControllerClient(csvc.url)
        for schema, cols, n_segs in [(ORDERS_SCHEMA, ORDERS, 4),
                                     (CUSTS_SCHEMA, CUSTS, 2),
                                     (REGIONS_SCHEMA, REGIONS, 1)]:
            cc.add_schema(schema)
            cfg = TableConfig(schema.name, replication=1)
            cc.add_table(cfg)
            n = len(next(iter(cols.values())))
            step = (n + n_segs - 1) // n_segs
            builder = SegmentBuilder(schema)
            for si, lo in enumerate(range(0, n, step)):
                seg = builder.build(_slice(cols, lo, lo + step),
                                    str(tmp / f"b_{schema.name}_{si}"),
                                    f"{schema.name}_{si}")
                cc.upload_segment(cfg.table_name_with_type, seg)

        def ready():
            served = [set() for _ in nodes]
            for ni, node in enumerate(nodes):
                for t in ("orders_OFFLINE", "custs_OFFLINE", "regions_OFFLINE"):
                    served[ni] |= {f"{t}:{s}" for s in node.segments_served(t)}
            return sum(len(s) for s in served) == 7
        assert wait_until(ready, timeout=30, interval=0.1)

        db = sqlite3.connect(":memory:", check_same_thread=False)
        db.execute("CREATE TABLE orders (cust_id TEXT, qty INTEGER, amount REAL)")
        db.execute("CREATE TABLE custs (cust_id TEXT, region TEXT, tier INTEGER)")
        db.execute("CREATE TABLE regions (region TEXT, zone TEXT)")
        db.executemany("INSERT INTO orders VALUES (?,?,?)",
                       list(zip(ORDERS["cust_id"], ORDERS["qty"].tolist(),
                                ORDERS["amount"].tolist())))
        db.executemany("INSERT INTO custs VALUES (?,?,?)",
                       list(zip(CUSTS["cust_id"], CUSTS["region"],
                                CUSTS["tier"].tolist())))
        db.executemany("INSERT INTO regions VALUES (?,?)",
                       list(zip(REGIONS["region"], REGIONS["zone"])))
        yield {"broker": broker, "bc": BrokerClient(bsvc.url), "db": db,
               "nodes": nodes}
    finally:
        for rc in catalogs:
            rc.close()
        for s in services:
            s.stop()


def _oracle(db, sql):
    import re
    return _sorted_rows(db.execute(re.sub(r" LIMIT \d+", "", sql)).fetchall())


def _query_rows(bc, sql):
    resp = bc.query(sql)
    if "error" in resp:
        raise RuntimeError(resp["error"])
    return resp, _sorted_rows([tuple(r) for r in resp["resultTable"]["rows"]])


def test_p2p_join_differential_vs_sqlite(shuffle_cluster):
    """Join results through the full server->server shuffle match sqlite, and
    the broker never buffers leaf rows (mailboxShuffle stat set, data-plane
    cap untouched)."""
    from test_differential_joins import gen_join_query
    bc, db = shuffle_cluster["bc"], shuffle_cluster["db"]
    boxes_before = len(REGISTRY._boxes)
    shuffle_cluster["broker"].max_data_plane_bytes = 1  # funnel would trip this
    try:
        rng = np.random.default_rng(77)
        for qi in range(12):
            sql = gen_join_query(rng)
            resp, got = _query_rows(bc, sql)
            assert resp.get("mailboxShuffle"), resp.keys()
            oracle = _oracle(db, sql)
            assert _rows_match(got, oracle, 1e-6, 1e-4), \
                f"q={qi}\n{sql}\nours({len(got)}): {got[:4]}\n" \
                f"oracle({len(oracle)}): {oracle[:4]}"
    finally:
        shuffle_cluster["broker"].max_data_plane_bytes = None
    # every mailbox of THESE queries drained (a failed query elsewhere in the
    # process may legitimately leave cancelled boxes for the TTL sweep, so
    # assert no growth rather than global emptiness)
    assert len(REGISTRY._boxes) <= boxes_before


def test_p2p_three_way_join_worker_to_worker(shuffle_cluster):
    """A 3-table join pipelines stage-0 worker output STRAIGHT to stage-1
    workers' mailboxes (no broker hop between stages)."""
    bc, db = shuffle_cluster["bc"], shuffle_cluster["db"]
    sql = ("SELECT r.zone, COUNT(*), SUM(o.amount) FROM orders o "
           "JOIN custs c ON o.cust_id = c.cust_id "
           "JOIN regions r ON c.region = r.region "
           "GROUP BY r.zone LIMIT 1000")
    shuffle_cluster["broker"].max_data_plane_bytes = 1
    try:
        resp, got = _query_rows(bc, sql)
    finally:
        shuffle_cluster["broker"].max_data_plane_bytes = None
    assert resp.get("mailboxShuffle")
    assert _rows_match(got, _oracle(db, sql), 1e-6, 1e-4)


def test_p2p_selection_join_order_limit(shuffle_cluster):
    """Selection (non-agg) join with ORDER BY + LIMIT: workers trim their
    partitions, the broker merges the trimmed partials."""
    bc, db = shuffle_cluster["bc"], shuffle_cluster["db"]
    sql = ("SELECT o.cust_id, o.amount, c.region FROM orders o "
           "JOIN custs c ON o.cust_id = c.cust_id "
           "ORDER BY o.amount DESC LIMIT 10")
    resp, _ = _query_rows(bc, sql)
    got = [tuple(r) for r in resp["resultTable"]["rows"]]
    oracle = shuffle_cluster["db"].execute(sql).fetchall()
    assert [round(r[1], 2) for r in got] == [round(r[1], 2) for r in oracle]


def test_funnel_fallback_option_and_data_plane_cap(shuffle_cluster):
    """OPTION(useMailboxShuffle=false) forces the legacy broker-funnel path;
    with a data-plane cap set, the funnel fails with a clear error while the
    mailbox path (default) still succeeds — the flat-broker-memory proof."""
    bc, db = shuffle_cluster["bc"], shuffle_cluster["db"]
    broker = shuffle_cluster["broker"]
    sql = ("SELECT c.region, COUNT(*) FROM orders o "
           "JOIN custs c ON o.cust_id = c.cust_id GROUP BY c.region "
           "LIMIT 100 OPTION(useMailboxShuffle=false)")
    resp, got = _query_rows(bc, sql)             # uncapped funnel still works
    assert "mailboxShuffle" not in resp
    assert _rows_match(got, _oracle(db, sql.split(" OPTION")[0]), 1e-6, 1e-4)

    from pinot_tpu.cluster.http_service import HttpError
    broker.max_data_plane_bytes = 4096           # far below the leaf output
    try:
        with pytest.raises((RuntimeError, HttpError),
                           match="data-plane memory cap"):
            _query_rows(bc, sql)
        # same query through the shuffle: broker data plane stays flat
        resp, got = _query_rows(bc, sql.split(" OPTION")[0])
        assert resp.get("mailboxShuffle")
        assert _rows_match(got, _oracle(db, sql.split(" OPTION")[0]),
                           1e-6, 1e-4)
    finally:
        broker.max_data_plane_bytes = None


def test_distributed_groupby_partitions_key_space(shuffle_cluster):
    """Single-table high-cardinality GROUP BY through the partitioned agg
    exchange: exact results, HAVING + ORDER + LIMIT handled by worker-side
    trim on disjoint key ranges."""
    bc, db = shuffle_cluster["bc"], shuffle_cluster["db"]
    boxes_before = len(REGISTRY._boxes)
    sql = ("SELECT cust_id, COUNT(*), SUM(amount) FROM orders "
           "GROUP BY cust_id LIMIT 100000 OPTION(useMultistageEngine=true)")
    resp, got = _query_rows(bc, sql)
    assert resp.get("distributedGroupBy")
    assert _rows_match(got, _oracle(db, sql.split(" OPTION")[0]), 1e-6, 1e-4)

    # ordered top-k with HAVING: the trim must not change results
    sql2 = ("SELECT cust_id, SUM(amount) AS total FROM orders GROUP BY cust_id "
            "HAVING total > 100 ORDER BY total DESC LIMIT 7 "
            "OPTION(useMultistageEngine=true)")
    resp2, _ = _query_rows(bc, sql2)
    got2 = [tuple(r) for r in resp2["resultTable"]["rows"]]
    oracle2 = db.execute(
        "SELECT cust_id, SUM(amount) AS total FROM orders GROUP BY cust_id "
        "HAVING total > 100 ORDER BY total DESC LIMIT 7").fetchall()
    assert resp2.get("distributedGroupBy")
    assert [r[0] for r in got2] == [r[0] for r in oracle2]
    assert np.allclose([r[1] for r in got2], [r[1] for r in oracle2])

    # identical answers with the distribution off
    _, plain = _query_rows(bc, sql.split(" OPTION")[0])
    assert _rows_match(got, plain, 1e-9, 1e-9)
    assert len(REGISTRY._boxes) <= boxes_before  # this test's boxes drained


def test_distributed_groupby_doc_threshold_auto_routes(shuffle_cluster):
    """The cluster-config doc threshold routes big tables automatically."""
    bc = shuffle_cluster["bc"]
    broker = shuffle_cluster["broker"]
    broker.catalog.put_property(
        "clusterConfig/broker.distributedGroupByDocThreshold", "100")
    try:
        resp, _ = _query_rows(
            bc, "SELECT cust_id, COUNT(*) FROM orders GROUP BY cust_id "
                "LIMIT 100000")
        assert resp.get("distributedGroupBy")
    finally:
        broker.catalog.put_property(
            "clusterConfig/broker.distributedGroupByDocThreshold", None)


def test_worker_death_mid_shuffle_fails_cleanly(shuffle_cluster):
    """A worker that dies mid-query must produce ONE clean error promptly
    (cancellation wakes all blocked peers) — never a hang. Simulated by
    cancelling the query's mailboxes everywhere mid-flight, which is exactly
    the unwind path a dead worker triggers."""
    bc = shuffle_cluster["bc"]
    sql = ("SELECT c.region, COUNT(*) FROM orders o "
           "JOIN custs c ON o.cust_id = c.cust_id GROUP BY c.region LIMIT 10")
    results = []

    def run():
        try:
            results.append(("ok", _query_rows(bc, sql)[1]))
        except Exception as e:
            results.append(("err", str(e)))

    # cancel continuously while the query runs: whichever stage it is in,
    # the cancellation lands mid-flight
    stop = threading.Event()

    def killer():
        while not stop.is_set():
            for key in list(REGISTRY._boxes):
                REGISTRY.cancel_query(key[0])
            time.sleep(0.002)

    kt = threading.Thread(target=killer, daemon=True)
    kt.start()
    t = threading.Thread(target=run)
    t.start()
    t.join(timeout=60)
    stop.set()
    kt.join(timeout=5)
    assert not t.is_alive(), "query hung after worker death"
    assert results
    kind, payload = results[0]
    if kind == "err":   # cancelled mid-shuffle: one clean error
        assert "cancel" in payload.lower() or "stage worker failed" in payload.lower() \
            or "truncated" in payload.lower(), payload
    REGISTRY._cancelled.clear()
    # the cluster still answers queries afterwards
    resp, _ = _query_rows(bc, sql)
    assert resp["resultTable"]["rows"]


def test_sigkill_worker_process_mid_shuffle(tmp_path):
    """Real OS-process chaos: SIGKILL a server that is simultaneously a leaf
    and a stage worker while a join is shuffling. The query must terminate
    promptly — clean error or (if the kill landed after its frames) a correct
    result — never a hang (reference: the v2 engine failing queries on
    stage-worker death)."""
    from pinot_tpu.cluster.process import ProcessCluster
    rng = np.random.default_rng(5)
    n = 60_000
    fact_cols = {
        "k": [f"u{i}" for i in rng.integers(0, 5000, n)],
        "v": rng.uniform(0, 1, n),
    }
    fact_schema = Schema("fact", [dimension("k"), metric("v", DataType.DOUBLE)])
    dim_schema = Schema("dims", [dimension("k"), dimension("grp")])
    dim_cols = {"k": [f"u{i}" for i in range(5000)],
                "grp": [f"g{i % 7}" for i in range(5000)]}
    with ProcessCluster(num_servers=2, work_dir=str(tmp_path)) as cluster:
        cluster.controller.add_schema(fact_schema)
        cluster.controller.add_schema(dim_schema)
        fcfg = TableConfig("fact", replication=1)
        dcfg = TableConfig("dims", replication=2)
        cluster.controller.add_table(fcfg)
        cluster.controller.add_table(dcfg)
        fb = SegmentBuilder(fact_schema)
        for si in range(4):
            seg = fb.build(_slice(fact_cols, si * n // 4, (si + 1) * n // 4),
                           str(tmp_path / f"fb{si}"), f"fact_{si}")
            cluster.controller.upload_segment(fcfg.table_name_with_type, seg)
        dseg = SegmentBuilder(dim_schema).build(
            dim_cols, str(tmp_path / "db"), "dims_0")
        cluster.controller.upload_segment(dcfg.table_name_with_type, dseg)

        def converged():
            st = cluster.controller.table_status(fcfg.table_name_with_type)
            return st.get("segments", 0) == 4 and st.get("converged")
        assert wait_until(converged, timeout=30)

        sql = ("SELECT d.grp, COUNT(*), SUM(f.v) FROM fact f "
               "JOIN dims d ON f.k = d.k GROUP BY d.grp LIMIT 100")
        results = []

        def run():
            try:
                results.append(("ok", cluster.query(sql)))
            except Exception as e:
                results.append(("err", f"{type(e).__name__}: {e}"))

        t = threading.Thread(target=run)
        t.start()
        time.sleep(0.4)               # let the shuffle get going
        cluster.procs["server_1"].kill()   # SIGKILL, no cleanup
        t.join(timeout=90)
        assert not t.is_alive(), "query hung after SIGKILL of a stage worker"
        kind, payload = results[0]
        if kind == "ok":
            if "error" in payload:
                assert any(s in str(payload["error"]) for s in
                           ("stage worker failed", "Connection", "cancel",
                            "truncated", "ConnectionError", "failed")), payload
            else:
                # kill landed after the worker's frames: result must be right
                assert payload["resultTable"]["rows"]
        else:
            assert payload  # clean python-side error, not a hang


def test_distributed_groupby_randomized_differential(shuffle_cluster):
    """Randomized single-table aggregations through the partitioned mailbox
    exchange vs sqlite3 — the same differential discipline the join paths
    get (seeded, multiple shapes: group-by, HAVING, ORDER+LIMIT, filters)."""
    bc, db = shuffle_cluster["bc"], shuffle_cluster["db"]
    rng = np.random.default_rng(4242)
    aggs = ["COUNT(*)", "SUM(amount)", "MIN(qty)", "MAX(qty)", "SUM(qty)"]
    for qi in range(15):
        pick = list(rng.choice(aggs, rng.integers(1, 4), replace=False))
        where = ""
        if rng.random() < 0.5:
            where = f" WHERE qty > {int(rng.integers(1, 15))}"
        if rng.random() < 0.3:
            c = f"amount < {round(float(rng.uniform(50, 450)), 2)}"
            where = where + (" AND " if where else " WHERE ") + c
        tail = ""
        if rng.random() < 0.4:
            tail = f" HAVING COUNT(*) > {int(rng.integers(1, 10))}"
            if "COUNT(*)" not in pick:
                pick.append("COUNT(*)")
        sql = (f"SELECT cust_id, {', '.join(pick)} FROM orders{where} "
               f"GROUP BY cust_id{tail} LIMIT 100000 "
               f"OPTION(useMultistageEngine=true)")
        resp, got = _query_rows(bc, sql)
        assert resp.get("distributedGroupBy"), sql
        oracle = _oracle(db, sql.split(" OPTION")[0])
        assert _rows_match(got, oracle, 1e-6, 1e-4), \
            f"q={qi}\n{sql}\nours({len(got)}): {got[:4]}\n" \
            f"oracle({len(oracle)}): {oracle[:4]}"


def test_p2p_three_way_randomized_differential(shuffle_cluster):
    """Randomized 3-table joins (worker-to-worker forwarding) vs sqlite3 —
    the multi-stage pipeline gets the same fuzz discipline as single joins."""
    bc, db = shuffle_cluster["bc"], shuffle_cluster["db"]
    rng = np.random.default_rng(909)
    for qi in range(8):
        jt1 = ["JOIN", "LEFT JOIN"][rng.integers(0, 2)]
        where = ""
        if rng.random() < 0.5:
            where = f" WHERE o.qty > {int(rng.integers(1, 15))}"
        agg = ["COUNT(*)", "SUM(o.amount)",
               "COUNT(*), SUM(o.amount), MIN(o.qty)"][rng.integers(0, 3)]
        sql = (f"SELECT r.zone, {agg} FROM orders o "
               f"{jt1} custs c ON o.cust_id = c.cust_id "
               f"JOIN regions r ON c.region = r.region{where} "
               f"GROUP BY r.zone LIMIT 1000")
        resp, got = _query_rows(bc, sql)
        assert resp.get("mailboxShuffle"), sql
        oracle = _oracle(db, sql)
        assert _rows_match(got, oracle, 1e-6, 1e-4), \
            f"q={qi}\n{sql}\nours: {got[:4]}\noracle: {oracle[:4]}"


def test_p2p_hybrid_table_time_boundary(tmp_path):
    """A HYBRID table (offline + realtime halves) queried through the P2P
    paths: the time-boundary split rides the leaf tasks' time filters, so
    rows copied realtime->offline are never double-counted."""
    import json as _json

    from pinot_tpu.cluster.process import ProcessCluster
    from pinot_tpu.ingest.kafkalite import LogBrokerClient, LogBrokerServer
    from pinot_tpu.schema import DataType, Schema, date_time, dimension, metric
    from pinot_tpu.table import StreamConfig, TableType

    DAY = 86400000
    t0 = 1700000000000
    schema = Schema("hy", [dimension("u", DataType.STRING),
                           metric("v", DataType.LONG),
                           date_time("ts", DataType.LONG)])
    srv = LogBrokerServer()
    try:
        client = LogBrokerClient(srv.bootstrap)
        client.create_topic("hy_t", 1)
        with ProcessCluster(num_servers=2, work_dir=str(tmp_path)) as cluster:
            cluster.controller.add_schema(schema)
            # OFFLINE half: days 0-1 (END of day1 becomes the boundary)
            off = TableConfig("hy", table_type=TableType.OFFLINE,
                              time_column="ts")
            cluster.controller.add_table(off)
            from pinot_tpu.segment.writer import SegmentBuilder
            b = SegmentBuilder(schema)
            cluster.controller.upload_segment(
                off.table_name_with_type,
                b.build({"u": [f"u{i % 3}" for i in range(60)],
                         "v": list(range(60)),
                         "ts": [t0 + (i % 2) * DAY for i in range(60)]},
                        str(tmp_path / "b"), "hy_0"))
            # REALTIME half: re-ingests day 1 (30 overlapping rows the
            # boundary must hide) + fresh day 2 rows
            rt = TableConfig("hy", table_type=TableType.REALTIME,
                             time_column="ts",
                             replication=1,
                             stream=StreamConfig(
                                 stream_type="kafkalite", topic="hy_t",
                                 properties={"bootstrap": srv.bootstrap},
                                 flush_threshold_rows=10_000))
            cluster.controller.add_table(rt, num_partitions=1)
            for i in range(30):
                client.produce("hy_t", _json.dumps(
                    {"u": f"u{i % 3}", "v": 1000 + i, "ts": t0 + DAY}))
            for i in range(40):
                client.produce("hy_t", _json.dumps(
                    {"u": f"u{i % 3}", "v": 2000 + i, "ts": t0 + 2 * DAY}))

            def counts():
                r = cluster.query("SELECT COUNT(*) FROM hy"
                                  )["resultTable"]["rows"]
                return r[0][0] if r else 0
            # boundary: offline answers <= day1, realtime answers > day1 —
            # total = 60 offline + 40 fresh realtime (30 overlaps hidden)
            assert wait_until(lambda: counts() == 100, timeout=60), counts()

            # distributed GROUP BY over the hybrid: same split, exact totals
            resp = cluster.query(
                "SELECT u, COUNT(*), SUM(v) FROM hy GROUP BY u ORDER BY u "
                "LIMIT 10 OPTION(useMultistageEngine=true)")
            rows = resp["resultTable"]["rows"]
            assert resp.get("distributedGroupBy"), resp.keys()
            assert sum(r[1] for r in rows) == 100
            want_sum = (sum(range(60))
                        + sum(2000 + i for i in range(40)))
            assert sum(r[2] for r in rows) == want_sum
    finally:
        srv.stop()
