"""KinesisLite tests: JSON-API wire shapes, shard consumption, sigv4, and a
realtime table consuming through the 'kinesis' stream plugin.

Mirrors the reference's Kinesis plugin coverage
(`pinot-plugins/pinot-stream-ingestion/pinot-kinesis/src/test/...`, which
runs against a kinesis mock the same way)."""

import base64
import json
import time

import numpy as np
import pytest

from pinot_tpu.ingest.kinesislite import (KinesisClient, KinesisConsumer,
                                          KinesisError, KinesisStub)

from conftest import wait_until


@pytest.fixture
def stub():
    s = KinesisStub()
    yield s
    s.stop()


def test_wire_shapes_and_put_get(stub):
    c = KinesisClient(stub.url)
    c.create_stream("events", 2)
    assert c.shard_count("events") == 2
    out = c.put_record("events", b"hello", "pk1")
    assert set(out) == {"ShardId", "SequenceNumber"}
    # records land on the shard crc32(pk) selects; same pk -> same shard
    out2 = c.put_record("events", b"world", "pk1")
    assert out2["ShardId"] == out["ShardId"]
    assert int(out2["SequenceNumber"]) == int(out["SequenceNumber"]) + 1

    shard = int(out["ShardId"].rsplit("-", 1)[-1])
    it = c.call("GetShardIterator", {
        "StreamName": "events", "ShardId": out["ShardId"],
        "ShardIteratorType": "TRIM_HORIZON"})["ShardIterator"]
    d = c.call("GetRecords", {"ShardIterator": it, "Limit": 100})
    assert [base64.b64decode(r["Data"]) for r in d["Records"]] == \
        [b"hello", b"world"]
    assert d["MillisBehindLatest"] == 0
    # unknown stream errors with the AWS error envelope
    with pytest.raises(KinesisError, match="ResourceNotFoundException"):
        c.put_record("nope", b"x", "k")


def test_consumer_contract_and_batching(stub):
    c = KinesisClient(stub.url)
    c.create_stream("t", 1)
    c.put_records("t", [("k", f"m{i}") for i in range(25)])
    consumer = KinesisConsumer(c, "t", 0)
    batch = consumer.fetch(0, 10)
    assert len(batch.messages) == 10 and batch.next_offset == 10
    assert batch.messages[0].value == "m0" and batch.messages[0].offset == 0
    batch2 = consumer.fetch(batch.next_offset, 100)
    assert len(batch2.messages) == 15 and batch2.next_offset == 25
    # caught up: an empty fetch keeps the offset (NextShardIterator cached —
    # steady-state polling is one RPC per fetch)
    empty = consumer.fetch(batch2.next_offset, 100)
    assert empty.messages == [] and empty.next_offset == 25
    # replay from a checkpoint re-anchors exactly (cache miss path)
    again = consumer.fetch(7, 3)
    assert [m.value for m in again.messages] == ["m7", "m8", "m9"]


def test_sigv4_enforced():
    stub = KinesisStub(access_key="AK", secret_key="SK")
    try:
        good = KinesisClient(stub.url, access_key="AK", secret_key="SK")
        good.create_stream("s", 1)
        good.put_record("s", b"x", "k")
        bad = KinesisClient(stub.url, access_key="AK", secret_key="WRONG")
        with pytest.raises(KinesisError, match="AccessDenied"):
            bad.put_record("s", b"x", "k")
        unsigned = KinesisClient(stub.url)
        with pytest.raises(KinesisError, match="AccessDenied"):
            unsigned.put_record("s", b"x", "k")
    finally:
        stub.stop()


def test_realtime_table_consumes_kinesis(tmp_path, stub):
    """A realtime table with stream_type='kinesis': the consumption FSM runs
    against the Kinesis wire UNCHANGED — shard discovery, per-shard sequence
    offsets, commit, replay (the SPI claim the reference makes for its
    Kinesis plugin)."""
    from pinot_tpu.cluster.enclosure import QuickCluster
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.table import StreamConfig, TableConfig, TableType

    c = KinesisClient(stub.url)
    c.create_stream("clicks", 2)
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    schema = Schema("ev", [dimension("u"), metric("n", DataType.LONG)])
    cfg = TableConfig("ev", table_type=TableType.REALTIME, replication=1,
                      stream=StreamConfig(stream_type="kinesis",
                                          topic="clicks", decoder="json",
                                          properties={"endpoint": stub.url},
                                          flush_threshold_rows=40))
    cluster.create_realtime_table(schema, cfg, c.shard_count("clicks"))
    total = 0
    for i in range(100):
        total += i
        c.put_record("clicks", json.dumps({"u": f"u{i % 5}", "n": i}),
                     partition_key=f"u{i % 5}")
    cluster.pump_realtime(cfg.table_name_with_type)
    res = cluster.query("SELECT COUNT(*), SUM(n) FROM ev")
    assert res.rows[0] == [100, total]
    # rows past the flush threshold commit segments and keep counting
    for i in range(30):
        c.put_record("clicks", json.dumps({"u": "late", "n": 1}), "late")

    def counted():
        cluster.pump_realtime(cfg.table_name_with_type)
        return cluster.query("SELECT COUNT(*) FROM ev").rows[0][0] == 130
    assert wait_until(counted, timeout=30)
