"""Multichip differential suite: every mesh-merged shape on the 8-virtual-
device CPU mesh (conftest forces xla_force_host_platform_device_count=8),
compared against the 1-device mesh and the host-reducer answers.

Comparison contract: keys, counts, and every non-float cell must be
byte-equal across paths; float aggregates tolerate 1e-4 relative error
(f32 partials accumulate in different orders across 8 shards vs 1 vs the
host merge loop). Dense-partial ARRAYS (counts, occupancy) are compared
byte-for-byte — the psum of integer per-shard counts is exact.
"""

import numpy as np
import pytest

from pinot_tpu.parallel import MeshQueryExecutor, default_mesh
from pinot_tpu.parallel.mesh import pad_slots, placement_slots, skew_pct
from pinot_tpu.query import stats as qstats
from pinot_tpu.query.aggregates import make_agg
from pinot_tpu.query.context import compile_query
from pinot_tpu.query.executor import ServerQueryExecutor
from pinot_tpu.query.reduce import merge_segment_results, reduce_to_result
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.segment import load_segment
from pinot_tpu.segment.writer import SegmentBuilder, build_aligned_segments

N_KEYS = 5000   # >= executor.DENSE_PARTIAL_MIN_GROUPS: forces the dense path
N_ROWS = 8 * 8192

HC_QUERY = ("SELECT k, SUM(v), COUNT(*) FROM hcdiff GROUP BY k "
            f"LIMIT {2 * N_KEYS}")
DISTINCT_QUERY = ("SELECT DISTINCTCOUNT(region), DISTINCTCOUNTHLL(k), "
                  "DISTINCTCOUNTTHETASKETCH(k) FROM hcdiff "
                  "WHERE q < 40 LIMIT 5")
GROUPED_DISTINCT_QUERY = ("SELECT region, DISTINCTCOUNT(q), "
                          "DISTINCTCOUNTHLL(k) FROM hcdiff GROUP BY region "
                          "ORDER BY region LIMIT 10")
TOPK_QUERY = "SELECT k, v FROM hcdiff ORDER BY v DESC LIMIT 10"


def _schema():
    return Schema("hcdiff", [
        dimension("k", DataType.INT),
        dimension("region", DataType.STRING),
        metric("q", DataType.INT),
        metric("v", DataType.DOUBLE),
    ])


def _columns(rng, n):
    # one full pass of every key so each segment slice still spans the whole
    # dictionary; distinct v values keep the top-k order deterministic
    k = np.concatenate([np.arange(N_KEYS, dtype=np.int64),
                        rng.integers(0, N_KEYS, n - N_KEYS)])
    rng.shuffle(k)
    return {
        "k": k.astype(np.int32),
        "region": np.array(["AFRICA", "AMERICA", "ASIA", "EUROPE", "ME"],
                           dtype=object)[rng.integers(0, 5, n)],
        "q": rng.integers(0, 100, n).astype(np.int32),
        "v": np.round(rng.uniform(0.0, 1000.0, n), 6),
    }


@pytest.fixture(scope="module")
def segments(tmp_path_factory):
    out = tmp_path_factory.mktemp("mc_aligned")
    paths = build_aligned_segments(_schema(), _columns(
        np.random.default_rng(29), N_ROWS), str(out), "hcdiff", 8)
    return [load_segment(p) for p in paths]


@pytest.fixture(scope="module")
def mesh8():
    return MeshQueryExecutor(default_mesh(8))


@pytest.fixture(scope="module")
def mesh1():
    return MeshQueryExecutor(default_mesh(1))


@pytest.fixture(scope="module")
def host():
    return ServerQueryExecutor(use_device=False)


def assert_rows_match(got, want, label, rel=1e-4):
    """Byte-equality for every non-float cell; `rel` tolerance for floats."""
    assert len(got) == len(want), \
        f"{label}: {len(got)} rows vs {len(want)}"
    for rg, rw in zip(got, want):
        assert len(rg) == len(rw), f"{label}: column count {rg} vs {rw}"
        for vg, vw in zip(rg, rw):
            if isinstance(vg, float) and isinstance(vw, float):
                assert abs(vg - vw) <= rel * max(1.0, abs(vw)), \
                    f"{label}: {vg} != {vw} in {rg} vs {rw}"
            else:
                assert vg == vw, f"{label}: {vg!r} != {vw!r}"


def _sorted(rows):
    return sorted(rows, key=lambda r: tuple(str(v) for v in r))


def _leaf_partial(mesh_exec, segments, sql):
    """The server-level mesh partial: one sharded launch, one fetch."""
    ctx = compile_query(sql, segments[0].schema)
    disp = mesh_exec.dispatch_partial(ctx, segments)
    assert disp is not None, f"{sql!r} did not plan on the mesh"
    outs_dev, decode = disp
    return ctx, decode(mesh_exec.fetch([outs_dev])[0])


# -- placement unit behavior -------------------------------------------------

def test_pad_slots_quantization():
    # multi-device: per-device slots quantize to pow2 (compile-cache buckets)
    assert pad_slots(5, 8) == 8
    assert pad_slots(9, 8) == 16
    assert pad_slots(17, 8) == 8 * 4
    # single device keeps the exact count — no rectangularity to buy
    assert pad_slots(5, 1) == 5
    assert pad_slots(17, 1) == 17


def test_placement_slots_lpt_balances_uneven_docs():
    docs = [20000, 15000, 10000, 5000, 5000]
    slots, loads = placement_slots(docs, pad_slots(len(docs), 8), 8)
    assert sorted(slots) == slots or len(set(slots)) == len(slots)
    assert len(set(slots)) == len(docs)           # distinct slots
    assert max(slots) < pad_slots(len(docs), 8)   # bounded by the block
    assert sum(loads) == sum(docs)
    # LPT with capacity 1/device: each segment lands on its own device,
    # biggest first — the max device load is the biggest single segment
    assert max(loads) == 20000
    assert skew_pct(loads) > 0.0
    assert skew_pct([100, 100, 100, 100]) == 0.0
    assert skew_pct([]) == 0.0


# -- mesh-merged shapes vs 1-device and host reducers ------------------------

@pytest.mark.parametrize("sql,label", [
    (HC_QUERY, "dense_groupby"),
    (DISTINCT_QUERY, "distinct_sketches"),
    (GROUPED_DISTINCT_QUERY, "grouped_distinct"),
])
def test_mesh8_vs_mesh1_vs_host(segments, mesh8, mesh1, host, sql, label):
    with qstats.collect_stats() as st:
        r8 = mesh8.execute(segments, sql)
    r1 = mesh1.execute(segments, sql)
    rh = host.execute(segments, sql)
    assert int(st.counters.get(qstats.DEVICE_LAUNCHES, 0)) == 1, \
        f"{label}: expected ONE sharded launch on the 8-device mesh"
    assert_rows_match(_sorted(r8.rows), _sorted(r1.rows), f"{label} 8v1")
    assert_rows_match(_sorted(r8.rows), _sorted(rh.rows), f"{label} 8vHost")


def test_topk_prepared_mesh_vs_mesh1_vs_host(segments, mesh8, mesh1, host):
    """The fused top-k rides the PREPARED pipeline path (one stacked launch
    over all segments); its reduced selection must match both mesh widths
    and the host engine."""
    from pinot_tpu.cluster.device_server import DEVICE_FALLBACK
    ctx = compile_query(TOPK_QUERY, segments[0].schema)

    def run(me):
        p = me.prepare_partial(ctx, segments)
        assert p is not None and p.kind == "topk"
        launches = me.dispatch_prepared([p])
        assert len(launches) == 1, "topk must be ONE stacked launch"
        outs_dev, finish, _ = launches[0]
        outs_list = finish(me.fetch([outs_dev])[0])
        partial = p.decode(outs_list[0])
        assert partial is not DEVICE_FALLBACK
        return reduce_to_result(
            ctx, merge_segment_results([partial], []), [], []).rows

    r8, r1 = run(mesh8), run(mesh1)
    rh = host.execute(segments, TOPK_QUERY).rows
    assert_rows_match(r8, r1, "topk 8v1")
    assert_rows_match(r8, rh, "topk 8vHost")


def test_dense_partial_byte_equal_across_mesh_widths(segments, mesh8, mesh1):
    """The high-card leaf partial must come back as a DensePartial from BOTH
    mesh widths — zero host-side value merges — with byte-equal integer
    arrays (psum of per-shard int counts is exact)."""
    _, leaf8 = _leaf_partial(mesh8, segments, HC_QUERY)
    _, leaf1 = _leaf_partial(mesh1, segments, HC_QUERY)
    assert leaf8.dense is not None and leaf1.dense is not None
    assert leaf8.dense.token == leaf1.dense.token
    np.testing.assert_array_equal(leaf8.dense.counts, leaf1.dense.counts)
    assert leaf8.num_docs_scanned == leaf1.num_docs_scanned == N_ROWS
    for name in leaf8.dense.outs:
        np.testing.assert_allclose(leaf8.dense.outs[name],
                                   leaf1.dense.outs[name], rtol=1e-5)


def test_device_routed_exchange_preserves_dense(segments, mesh8):
    """P=1 — the partition count the device-routed coordinator collapses to
    when every stage worker shares the mesh — must carry the array-form
    partial through the REAL mailbox fabric untouched (byte-equal arrays,
    no densify)."""
    from pinot_tpu.multistage.shuffle import (_deliver_local, consume_mailbox,
                                              partition_groups_stable)
    ctx, leaf = _leaf_partial(mesh8, segments, HC_QUERY)
    aggs = [make_agg(f) for f in ctx.aggregations]
    assert leaf.dense is not None
    ref_counts = leaf.dense.counts.copy()
    ref_outs = {k: v.copy() for k, v in leaf.dense.outs.items()}

    parts = partition_groups_stable(leaf, 1)
    assert len(parts) == 1 and parts[0].dense is not None
    _deliver_local("mcq1", "A.0", parts[0], "partial", "s0")
    _, partials = consume_mailbox("mcq1", "A.0", 1)
    merged = merge_segment_results(partials, aggs)
    assert merged.dense is not None, "exchange densified the partial"
    np.testing.assert_array_equal(merged.dense.counts, ref_counts)
    for name, ref in ref_outs.items():
        np.testing.assert_array_equal(merged.dense.outs[name], ref)


def test_hash_exchange_matches_direct_reduce(segments, mesh8):
    """P=4 hash partition -> mailbox -> merge must reduce to the same table
    as reducing the leaf partial directly (keys are disjoint across
    partitions, so merged states are bit-identical)."""
    from pinot_tpu.multistage.shuffle import (_deliver_local, consume_mailbox,
                                              partition_groups_stable)
    ctx, leaf = _leaf_partial(mesh8, segments, HC_QUERY)
    aggs = [make_agg(f) for f in ctx.aggregations]
    direct = reduce_to_result(
        ctx, merge_segment_results([leaf], aggs), aggs, list(ctx.group_by))

    parts = partition_groups_stable(leaf, 4)     # materializes the dense form
    for i, part in enumerate(parts):
        _deliver_local("mcq4", f"A.{i}", part, "partial", "s0")
    got = []
    for i in range(4):
        _, partials = consume_mailbox("mcq4", f"A.{i}", 1)
        got.extend(partials)
    exchanged = reduce_to_result(
        ctx, merge_segment_results(got, aggs), aggs, list(ctx.group_by))
    assert_rows_match(_sorted(exchanged.rows), _sorted(direct.rows),
                      "hash_exchange", rel=1e-7)


def test_shuffle_join_matches_host_computation():
    """The multistage shuffle-join runtime (leaf scan -> hash exchange ->
    per-partition join -> reduce) against a direct numpy evaluation."""
    from pinot_tpu.multistage import execute_multistage
    from pinot_tpu.multistage.runtime import make_segment_scan

    rng = np.random.default_rng(61)
    n = 4000
    orders_schema = Schema("orders", [
        dimension("cust_id", DataType.INT),
        metric("amount", DataType.DOUBLE)])
    custs_schema = Schema("custs", [
        dimension("cust_id", DataType.INT),
        dimension("tier", DataType.STRING)])
    orders = {"cust_id": rng.integers(0, 500, n).astype(np.int32),
              "amount": np.round(rng.uniform(1.0, 50.0, n), 2)}
    tiers = np.array(["gold", "silver", "bronze"], dtype=object)
    custs = {"cust_id": np.arange(500, dtype=np.int32),
             "tier": tiers[rng.integers(0, 3, 500)]}
    import tempfile
    work = tempfile.mkdtemp(prefix="mc_join_")
    o_segs = [load_segment(p) for p in build_aligned_segments(
        orders_schema, orders, work, "orders", 4)]
    c_seg = load_segment(SegmentBuilder(custs_schema).build(
        custs, work, "custs_0"))
    res = execute_multistage(
        "SELECT c.tier, SUM(o.amount), COUNT(*) FROM orders o "
        "JOIN custs c ON o.cust_id = c.cust_id "
        "GROUP BY c.tier ORDER BY c.tier LIMIT 10",
        make_segment_scan({"orders": o_segs, "custs": [c_seg]}),
        {"orders": orders_schema, "custs": custs_schema}.get)

    cust_tier = dict(zip(custs["cust_id"].tolist(), custs["tier"].tolist()))
    want = {}
    for cid, amt in zip(orders["cust_id"].tolist(),
                        orders["amount"].tolist()):
        t = cust_tier[cid]
        s, c = want.get(t, (0.0, 0))
        want[t] = (s + amt, c + 1)
    want_rows = [[t, want[t][0], want[t][1]] for t in sorted(want)]
    assert_rows_match(res.rows, want_rows, "shuffle_join", rel=1e-9)


# -- uneven segment placement ------------------------------------------------

@pytest.fixture(scope="module")
def uneven_segments(tmp_path_factory):
    """5 ALIGNED segments with very different sizes over the 8-device mesh:
    exercises LPT placement (chip-aware slots), empty device slots, and the
    skew accounting — dictionaries are shared across segments exactly like
    build_aligned_segments so the dense path stays eligible."""
    from pinot_tpu.segment.dictionary import build_dictionary
    schema = _schema()
    rng = np.random.default_rng(47)
    sizes = (20000, 15000, 10000, 5000, 5000)
    union = _columns(rng, sum(sizes))
    fixed = {}
    for spec in schema.fields:
        fixed[spec.name], _ = build_dictionary(
            np.asarray(union[spec.name]) if spec.data_type.is_numeric
            else union[spec.name], spec.data_type)
    out = tmp_path_factory.mktemp("mc_uneven")
    builder = SegmentBuilder(schema)
    segs, lo = [], 0
    for i, sz in enumerate(sizes):
        part = {c: v[lo:lo + sz] for c, v in union.items()}
        segs.append(load_segment(builder.build(
            part, str(out), f"hcdiff_{i}", fixed_dictionaries=fixed)))
        lo += sz
    return segs


@pytest.mark.parametrize("sql,label", [
    (HC_QUERY, "uneven_dense_groupby"),
    ("SELECT region, SUM(v), COUNT(*), MAX(q) FROM hcdiff "
     "GROUP BY region ORDER BY region LIMIT 10", "uneven_lowcard_groupby"),
    ("SELECT SUM(v), COUNT(*) FROM hcdiff WHERE q < 30 LIMIT 5",
     "uneven_scalar"),
])
def test_uneven_segment_counts_match_host(uneven_segments, mesh8, host,
                                          sql, label):
    with qstats.collect_stats() as st:
        r8 = mesh8.execute(uneven_segments, sql)
    rh = host.execute(uneven_segments, sql)
    assert_rows_match(_sorted(r8.rows), _sorted(rh.rows), label)
    # 5 unequal segments on 8 devices: the LPT loads are necessarily skewed,
    # and the max-merged stat must surface that (not sum across launches)
    skew = float(st.counters.get(qstats.DEVICE_SKEW_PCT, 0.0))
    assert skew > 0.0, f"{label}: expected nonzero deviceSkewPct"
    assert int(st.counters.get(qstats.DEVICE_LAUNCHES, 0)) == 1


# -- unaligned (merged-view) sets on the mesh ---------------------------------

def test_merged_view_identity_remap_and_answers(tmp_path_factory, mesh8,
                                                host):
    """UNALIGNED segments ride the merged-dictionary path. A member whose
    dictionary already equals the global union must get remap None (its ids
    are global already — the stacker skips the gather); members with partial
    dictionaries get real translation tables. Either way the mesh answer
    matches the host engine."""
    from pinot_tpu.parallel.merged import MergedSegmentView
    schema = _schema()
    rng = np.random.default_rng(83)
    out = tmp_path_factory.mktemp("mc_unaligned")
    builder = SegmentBuilder(schema)
    regions = np.array(["AFRICA", "AMERICA", "ASIA", "EUROPE", "ME"],
                       dtype=object)

    def make(name, keys, n):
        k = np.concatenate([keys, rng.choice(keys, n - len(keys))])
        rng.shuffle(k)
        cols = {"k": k.astype(np.int32),
                "region": regions[rng.integers(0, 5, n)],
                "q": rng.integers(0, 100, n).astype(np.int32),
                "v": np.round(rng.uniform(0.0, 1000.0, n), 6)}
        return load_segment(builder.build(cols, str(out), name))

    # seg0 spans every key (dict == union); seg1/seg2 see disjoint subsets
    segs = [make("full_0", np.arange(300, dtype=np.int64), 4000),
            make("low_1", np.arange(0, 100, dtype=np.int64), 3000),
            make("high_2", np.arange(200, 300, dtype=np.int64), 3000)]

    remaps = MergedSegmentView(segs).remap("k")
    assert remaps is not None
    assert remaps[0] is None, "full-union member should skip the remap gather"
    assert remaps[1] is not None and remaps[2] is not None
    np.testing.assert_array_equal(remaps[1], np.arange(100))
    np.testing.assert_array_equal(remaps[2], np.arange(200, 300))

    sql = ("SELECT k, SUM(v), COUNT(*) FROM hcdiff GROUP BY k "
           "ORDER BY k LIMIT 400")
    r8 = mesh8.execute(segs, sql)
    rh = host.execute(segs, sql)
    assert_rows_match(_sorted(r8.rows), _sorted(rh.rows), "merged_view")
