"""Randomized differential testing: this engine vs sqlite3 as the oracle.

Reference pattern: the reference cross-checks its two engines against each
other and against H2 in integration tests (`BaseQueriesTest`,
OfflineClusterIntegrationTest's H2 comparisons). Here the oracle is stdlib
sqlite3: generate random queries in the shared SQL dialect, run them on BOTH
engines over identical data, and compare row sets (float tolerances per path —
see TOL). Runs device + host paths, so it differentially checks THREE
implementations per query.

Seeded, so failures reproduce; the generator prints the SQL on mismatch.
"""

import math
import sqlite3

import numpy as np
import pytest

from pinot_tpu.query.executor import ServerQueryExecutor
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.segment.reader import load_segment
from pinot_tpu.segment.writer import SegmentBuilder, SegmentGeneratorConfig

N = 3000
RNG = np.random.default_rng(20260730)

COLS = {
    "dim_a": [f"a{i}" for i in RNG.integers(0, 12, N)],
    "dim_b": [f"b{i}" for i in RNG.integers(0, 5, N)],
    "num_i": RNG.integers(-50, 50, N).astype(np.int32),
    "num_j": RNG.integers(0, 1000, N).astype(np.int32),
    "val_x": np.round(RNG.uniform(-100, 100, N), 3),
    "val_y": np.round(RNG.exponential(10, N), 3),
}

SCHEMA = Schema("diff", [
    dimension("dim_a"), dimension("dim_b"),
    metric("num_i", DataType.INT), metric("num_j", DataType.INT),
    metric("val_x", DataType.DOUBLE), metric("val_y", DataType.DOUBLE),
])


@pytest.fixture(scope="module")
def engines(tmp_path_factory):
    import re as _re
    tmp = tmp_path_factory.mktemp("diff")
    # fst on the dims: the trigram regex prefilter runs differentially too
    seg = load_segment(SegmentBuilder(SCHEMA, SegmentGeneratorConfig(
        fst_index_columns=["dim_a", "dim_b"]))
                       .build({k: (v.copy() if isinstance(v, np.ndarray) else
                                   list(v)) for k, v in COLS.items()},
                              str(tmp), "diff_0"))
    db = sqlite3.connect(":memory:")
    db.execute("PRAGMA case_sensitive_like=ON")
    # same spelling works in both dialects: our engine's REGEXP_LIKE(col, 'p')
    # is a plain 2-arg function call sqlite can provide
    db.create_function(
        "regexp_like", 2,
        lambda v, p: int(v is not None and _re.search(p, str(v)) is not None))
    db.execute("CREATE TABLE diff (dim_a TEXT, dim_b TEXT, num_i INTEGER, "
               "num_j INTEGER, val_x REAL, val_y REAL)")
    rows = list(zip(COLS["dim_a"], COLS["dim_b"],
                    COLS["num_i"].tolist(), COLS["num_j"].tolist(),
                    COLS["val_x"].tolist(), COLS["val_y"].tolist()))
    db.executemany("INSERT INTO diff VALUES (?,?,?,?,?,?)", rows)
    return seg, db


# -- random query generator (shared pinot_tpu/sqlite dialect) -----------------

DIMS = ["dim_a", "dim_b"]
NUMS = ["num_i", "num_j", "val_x", "val_y"]
AGGS = ["COUNT(*)", "SUM({c})", "MIN({c})", "MAX({c})", "AVG({c})"]


# regex fragments over the a0..a13 / b0..b6 value space: literals long enough
# for the trigram index, plus shapes it must decline (alternation, anchors,
# classes) — differential over indexed AND fallback paths
_REGEXES = ["a1", "^a1$", "a1[0-3]", "a(1|2)", "^b[0-2]", "a1.*", "b[46]",
            "nope", "^a\\d+$", "a1|b2",
            # >=3-char required literals that MATCH real values (a10..a13):
            # the trigram index's non-empty candidate/intersect path runs
            "a10", "^a11$", "a12.*", "a13"]
_LIKES = ["a1%", "%1", "a_", "b%", "%a%", "a1"]


def _rand_pred(rng) -> str:
    kind = rng.integers(0, 8)
    if kind == 6:
        c = DIMS[rng.integers(0, len(DIMS))]
        return f"REGEXP_LIKE({c}, '{_REGEXES[rng.integers(0, len(_REGEXES))]}')"
    if kind == 7:
        c = DIMS[rng.integers(0, len(DIMS))]
        return f"{c} LIKE '{_LIKES[rng.integers(0, len(_LIKES))]}'"
    if kind == 0:
        c = DIMS[rng.integers(0, len(DIMS))]
        v = f"a{rng.integers(0, 14)}" if c == "dim_a" else f"b{rng.integers(0, 7)}"
        return f"{c} = '{v}'"
    if kind == 1:
        c = DIMS[rng.integers(0, len(DIMS))]
        vals = ", ".join(f"'{p}{i}'" for p, i in
                         [("a" if c == "dim_a" else "b", rng.integers(0, 14))
                          for _ in range(int(rng.integers(1, 4)))])
        return f"{c} IN ({vals})"
    c = NUMS[rng.integers(0, len(NUMS))]
    v = round(float(rng.uniform(-60, 60)), 2)
    if kind == 2:
        return f"{c} > {v}"
    if kind == 3:
        return f"{c} <= {v}"
    if kind == 4:
        lo = round(float(rng.uniform(-60, 0)), 2)
        hi = round(float(rng.uniform(0, 60)), 2)
        return f"{c} BETWEEN {lo} AND {hi}"
    return f"NOT {c} < {v}"


def _rand_where(rng) -> str:
    n = int(rng.integers(0, 4))
    if n == 0:
        return ""
    preds = [_rand_pred(rng) for _ in range(n)]
    glue = [" AND " if rng.random() < 0.6 else " OR " for _ in range(n - 1)]
    out = preds[0]
    for g, p in zip(glue, preds[1:]):
        out += g + p
    return " WHERE " + out


def gen_query(rng) -> str:
    where = _rand_where(rng)
    if rng.random() < 0.5:
        # scalar aggregation
        aggs = [AGGS[rng.integers(0, len(AGGS))].format(
            c=NUMS[rng.integers(0, len(NUMS))]) for _ in range(int(rng.integers(1, 4)))]
        return f"SELECT {', '.join(dict.fromkeys(aggs))} FROM diff{where}"
    # group-by
    keys = list(dict.fromkeys(
        DIMS[rng.integers(0, len(DIMS))] for _ in range(int(rng.integers(1, 3)))))
    aggs = list(dict.fromkeys(
        AGGS[rng.integers(0, len(AGGS))].format(c=NUMS[rng.integers(0, len(NUMS))])
        for _ in range(int(rng.integers(1, 3)))))
    return (f"SELECT {', '.join(keys + aggs)} FROM diff{where} "
            f"GROUP BY {', '.join(keys)} LIMIT 100000")


# -- comparison ---------------------------------------------------------------

def _norm_cell(v):
    if v is None:
        return None
    if isinstance(v, (float, np.floating)):
        f = float(v)
        if math.isnan(f):
            return None
        return f
    if isinstance(v, (int, np.integer)):
        return float(v)
    return v


def _rows_match(a, b, rel: float, abs_: float) -> bool:
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        if len(ra) != len(rb):
            return False
        for x, y in zip(ra, rb):
            if isinstance(x, float) and isinstance(y, float):
                if not math.isclose(x, y, rel_tol=rel, abs_tol=abs_):
                    return False
            elif x != y:
                return False
    return True


# device partials accumulate in f32: SUM over n values of magnitude M carries
# ~n*M*eps32 absolute error (0.04 for this dataset), and a near-cancelling sum
# has unbounded RELATIVE error — so the device comparison needs the abs term.
# The host path is f64 end-to-end and must match the oracle almost exactly.
TOL = {True: (1e-5, 0.05), False: (1e-9, 1e-6)}


def _sorted_rows(rows):
    return sorted([[_norm_cell(v) for v in r] for r in rows],
                  key=lambda r: [(x is None, str(type(x)), x) for x in r])


EXPRS = [  # expressions valid in BOTH dialects, deterministic results
    "UPPER(dim_a)", "LOWER(dim_b)", "LENGTH(dim_a)",
    "num_i + num_j", "val_x * 2", "ABS(num_i)",
]


def gen_expr_query(rng) -> str:
    """Transform expressions in SELECT and numeric-expression filters."""
    e = EXPRS[rng.integers(0, len(EXPRS))]
    where = _rand_where(rng)
    extra = ""
    if rng.random() < 0.5:
        extra = (" AND " if where else " WHERE ") + \
            f"num_i + num_j > {int(rng.integers(0, 800))}"
    cols = ["dim_a", "num_i", e]
    return (f"SELECT {', '.join(cols)} FROM diff{where}{extra} "
            f"ORDER BY {', '.join(cols)} LIMIT {int(rng.integers(1, 40))}")


@pytest.mark.parametrize("seed", range(3))
def test_differential_expressions_vs_sqlite(engines, seed):
    seg, db = engines
    rng = np.random.default_rng(9000 + seed)
    for qi in range(15):
        sql = gen_expr_query(rng)
        oracle = [[_norm_cell(v) for v in r] for r in db.execute(sql).fetchall()]
        for use_device in (True, False):
            got = [[_norm_cell(v) for v in r]
                   for r in ServerQueryExecutor(use_device=use_device)
                   .execute([seg], sql).rows]
            rel, abs_ = TOL[use_device]
            assert _rows_match(got, oracle, rel, abs_), (
                f"EXPR MISMATCH seed={seed} q={qi} device={use_device}\n{sql}\n"
                f"ours({len(got)}): {got[:4]}\noracle({len(oracle)}): {oracle[:4]}")


def gen_ordered_query(rng) -> str:
    """Shapes with a TOTAL order (ties broken by every selected column), so the
    ordered row list compares 1:1 against sqlite."""
    kind = rng.integers(0, 3)
    where = _rand_where(rng)
    if kind == 0:
        # selection with deterministic ORDER BY over all selected columns
        cols = ["num_j", "dim_a", "val_y"]
        lim = int(rng.integers(1, 50))
        return (f"SELECT {', '.join(cols)} FROM diff{where} "
                f"ORDER BY {', '.join(cols)} LIMIT {lim}")
    if kind == 1:
        # group-by ordered by its full key set + HAVING
        keys = ["dim_a", "dim_b"]
        c = NUMS[rng.integers(0, len(NUMS))]
        k = int(rng.integers(1, 40))
        return (f"SELECT {', '.join(keys)}, COUNT(*), SUM({c}) FROM diff{where} "
                f"GROUP BY {', '.join(keys)} HAVING COUNT(*) > {k} "
                f"ORDER BY {', '.join(keys)} LIMIT 100000")
    # DISTINCT with a total order
    keys = ["dim_b", "dim_a"] if rng.random() < 0.5 else ["dim_a"]
    lim = int(rng.integers(1, 30))
    return (f"SELECT DISTINCT {', '.join(keys)} FROM diff{where} "
            f"ORDER BY {', '.join(keys)} LIMIT {lim}")


@pytest.mark.parametrize("seed", range(4))
def test_differential_ordered_vs_sqlite(engines, seed):
    """ORDER BY / LIMIT / OFFSET / HAVING / DISTINCT with total orders: the
    ordered row lists must match positionally."""
    seg, db = engines
    rng = np.random.default_rng(5000 + seed)
    for qi in range(20):
        sql = gen_ordered_query(rng)
        oracle = [[_norm_cell(v) for v in r] for r in db.execute(sql).fetchall()]
        for use_device in (True, False):
            got = [[_norm_cell(v) for v in r]
                   for r in ServerQueryExecutor(use_device=use_device)
                   .execute([seg], sql).rows]
            rel, abs_ = TOL[use_device]
            assert _rows_match(got, oracle, rel, abs_), (
                f"ORDERED MISMATCH seed={seed} q={qi} device={use_device}\n{sql}\n"
                f"ours({len(got)}): {got[:5]}\noracle({len(oracle)}): {oracle[:5]}")


@pytest.mark.parametrize("seed", range(8))
def test_differential_vs_sqlite(engines, seed):
    seg, db = engines
    rng = np.random.default_rng(1000 + seed)
    for qi in range(25):
        sql = gen_query(rng)
        oracle = _sorted_rows(db.execute(sql.replace(" LIMIT 100000", "")
                                         ).fetchall())
        for use_device in (True, False):
            got = ServerQueryExecutor(use_device=use_device).execute(
                [seg], sql).rows
            got = _sorted_rows(got)
            rel, abs_ = TOL[use_device]
            assert _rows_match(got, oracle, rel, abs_), (
                f"MISMATCH seed={seed} q={qi} device={use_device}\n{sql}\n"
                f"ours({len(got)}): {got[:5]}\noracle({len(oracle)}): {oracle[:5]}")


def test_differential_multi_segment(engines, tmp_path):
    """The same oracle check across a SPLIT segment set (merge paths)."""
    _, db = engines
    from pinot_tpu.segment.writer import build_aligned_segments
    dirs = build_aligned_segments(
        SCHEMA, {k: (v.copy() if isinstance(v, np.ndarray) else list(v))
                 for k, v in COLS.items()}, str(tmp_path), "diffm", 4)
    segs = [load_segment(d) for d in dirs]
    rng = np.random.default_rng(77)
    for _ in range(10):
        sql = gen_query(rng)
        oracle = _sorted_rows(db.execute(sql.replace(" LIMIT 100000", "")
                                         ).fetchall())
        got = _sorted_rows(ServerQueryExecutor().execute(segs, sql).rows)
        assert _rows_match(got, oracle, *TOL[True]), \
            f"multi-segment mismatch:\n{sql}"
