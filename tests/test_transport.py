"""Transport tests: wire codec, HTTP services, and a real multi-process cluster.

Mirrors the reference's transport coverage: DataTable serde tests
(`pinot-core/src/test/.../datatable/`), `QueryRoutingTest` (broker->server dispatch),
and `OfflineClusterIntegrationTest` (multi-role cluster + queries + failures).
"""

import os

import numpy as np
import pytest

from pinot_tpu.cluster.catalog import Catalog
from pinot_tpu.cluster.controller import Controller
from pinot_tpu.cluster.deepstore import LocalDeepStore
from pinot_tpu.cluster.broker import Broker
from pinot_tpu.cluster.process import ControllerClient, ProcessCluster
from pinot_tpu.cluster.remote import (ControllerDeepStore, RemoteCatalog,
                                      RemoteServerHandle)
from pinot_tpu.cluster.server import ServerNode
from pinot_tpu.cluster.services import (BrokerService, ControllerService,
                                        ServerService)
from pinot_tpu.cluster.wire import (decode_segment_result, decode_value,
                                    encode_segment_result, encode_value)
from pinot_tpu.query.reduce import SegmentResult
from pinot_tpu.query.sketches import TDigest, ThetaSketch
from pinot_tpu.schema import DataType, FieldSpec, Schema
from pinot_tpu.segment.writer import SegmentBuilder, SegmentGeneratorConfig
from pinot_tpu.table import TableConfig


# -- wire codec --------------------------------------------------------------

def test_wire_value_roundtrip():
    cases = [
        None, True, False, 0, -1, 1 << 40, -(1 << 70), 3.5, float("inf"),
        "héllo", b"\x00\xffbytes", (1, "a", None), [1, [2, [3]]],
        {"k": (1, 2), "n": None}, {1, 2, 3}, (),
    ]
    for v in cases:
        assert decode_value(encode_value(v)) == v, v


def test_wire_ndarray_roundtrip():
    for arr in [np.arange(12, dtype=np.int32).reshape(3, 4),
                np.array([1.5, 2.5], dtype=np.float64),
                np.array([True, False]),
                np.zeros((0,), dtype=np.int64)]:
        out = decode_value(encode_value(arr))
        assert isinstance(out, np.ndarray)
        assert out.dtype == arr.dtype and out.shape == arr.shape
        assert np.array_equal(out, arr)


def test_wire_sketch_objects():
    theta = ThetaSketch.from_values(np.array(["a", "b", "c"], dtype=object))
    td = TDigest.from_values(np.arange(100.0))
    out = decode_value(encode_value([theta, td]))
    assert round(out[0].estimate()) == 3
    assert abs(out[1].quantile(0.5) - 49.5) < 5


def test_segment_result_roundtrip():
    r = SegmentResult("groups")
    r.num_docs_scanned = 42
    r.groups = {("a", 1): [3.0, (2.0, 5)], ("b", 2): [1.0, (1.0, 1)]}
    out = decode_segment_result(encode_segment_result(r))
    assert out.kind == "groups"
    assert out.num_docs_scanned == 42
    assert out.groups == r.groups

    sel = SegmentResult("selection")
    sel.rows = [(1, "x"), (2, "y")]
    sel.sort_keys = [(1,), (2,)]
    out = decode_segment_result(encode_segment_result(sel))
    assert out.rows == sel.rows and out.sort_keys == sel.sort_keys


# -- single-process HTTP cluster (every hop over localhost HTTP) -------------

SCHEMA = Schema("trips", [
    FieldSpec("city", DataType.STRING),
    FieldSpec("fare", DataType.DOUBLE),
    FieldSpec("n", DataType.INT),
])


def _build_segment(tmp, name, cities, fares, ns):
    builder = SegmentBuilder(SCHEMA, SegmentGeneratorConfig())
    return builder.build(
        {"city": np.array(cities, dtype=object),
         "fare": np.array(fares, dtype=np.float64),
         "n": np.array(ns, dtype=np.int32)},
        str(tmp), name)


@pytest.fixture
def http_cluster(tmp_path):
    """Controller + 2 servers + broker in one process, every call over HTTP."""
    catalog = Catalog()
    deepstore = LocalDeepStore(str(tmp_path / "deepstore"))
    controller = Controller("controller_0", catalog, deepstore,
                            str(tmp_path / "ctrl"))
    csvc = ControllerService(controller)
    services = [csvc]
    catalogs = []
    servers = []
    try:
        for i in range(2):
            rc = RemoteCatalog(csvc.url, poll_timeout_s=1.0)
            catalogs.append(rc)
            node = ServerNode(f"server_{i}", rc, ControllerDeepStore(csvc.url),
                              str(tmp_path / f"server_{i}"))
            ssvc = ServerService(node)
            services.append(ssvc)
            servers.append((node, rc, ssvc))
        brc = RemoteCatalog(csvc.url, poll_timeout_s=1.0)
        catalogs.append(brc)
        broker = Broker("broker_0", brc)
        bsvc = BrokerService(broker)
        services.append(bsvc)
        yield {"controller": controller, "csvc": csvc, "servers": servers,
               "broker": broker, "bsvc": bsvc, "tmp": tmp_path}
    finally:
        for rc in catalogs:
            rc.close()
        for s in services:
            s.stop()


def _wait_until(fn, timeout=15.0):
    from conftest import wait_until
    return wait_until(fn, timeout=timeout, interval=0.05, swallow=())


def test_http_cluster_query(http_cluster):
    c = ControllerClient(http_cluster["csvc"].url)
    c.add_schema(SCHEMA)
    cfg = TableConfig("trips", replication=2)
    c.add_table(cfg)
    seg1 = _build_segment(http_cluster["tmp"] / "b1", "trips_0",
                          ["nyc", "sf", "nyc"], [10.0, 20.0, 30.0], [1, 2, 3])
    seg2 = _build_segment(http_cluster["tmp"] / "b2", "trips_1",
                          ["sf", "la"], [5.0, 7.0], [4, 5])
    c.upload_segment(cfg.table_name_with_type, seg1)
    c.upload_segment(cfg.table_name_with_type, seg2)

    # wait for both remote servers to converge on the ideal state
    assert _wait_until(lambda: all(
        len(node.segments_served(cfg.table_name_with_type)) == 2
        for node, _, _ in http_cluster["servers"]))

    from pinot_tpu.cluster.process import BrokerClient
    bc = BrokerClient(http_cluster["bsvc"].url)
    # retry: the broker's catalog mirror polls — the first query can race the
    # external-view convergence even after both servers report loaded
    expected = [["nyc", 40.0], ["sf", 25.0], ["la", 7.0]]

    def rows():
        try:
            return bc.query("SELECT city, SUM(fare) AS total FROM trips "
                            "GROUP BY city ORDER BY total DESC"
                            )["resultTable"]["rows"]
        except Exception:   # mirror not converged yet: broker 500s -> retry
            return None
    assert _wait_until(lambda: rows() == expected)
    assert rows() == expected

    resp = bc.query("SELECT COUNT(*) FROM trips WHERE fare > 6")
    assert resp["resultTable"]["rows"][0][0] == 4

    # OPTION(trace=true): remote servers ship their span rows back on the wire and
    # the broker splices them under a server:<id>/ prefix (DataTable TRACE_INFO)
    resp = bc.query("SELECT COUNT(*) FROM trips OPTION(trace=true)")
    names = [s["name"] for s in resp["traceInfo"]]
    assert any(n.startswith("server:server_") and "/segment:" in n for n in names)

    # /metrics on every role serves the Prometheus exposition of the registry
    from pinot_tpu.cluster.http_service import http_call
    for svc in (http_cluster["csvc"], http_cluster["bsvc"]):
        text = http_call("GET", f"{svc.url}/metrics").decode()
        assert "pinot_broker_queries" in text  # one process => shared registry


def test_http_cluster_multistage_join(http_cluster):
    """JOIN through the broker with leaf scans dispatched to HTTP servers."""
    c = ControllerClient(http_cluster["csvc"].url)
    c.add_schema(SCHEMA)
    dim_schema = Schema("cities", [
        FieldSpec("city", DataType.STRING),
        FieldSpec("state", DataType.STRING),
    ])
    c.add_schema(dim_schema)
    cfg = TableConfig("trips", replication=2)
    c.add_table(cfg)
    dim_cfg = TableConfig("cities", replication=2)
    c.add_table(dim_cfg)

    seg = _build_segment(http_cluster["tmp"] / "b1", "trips_0",
                         ["nyc", "sf", "nyc"], [10.0, 20.0, 30.0], [1, 2, 3])
    c.upload_segment(cfg.table_name_with_type, seg)
    dim_builder = SegmentBuilder(dim_schema, SegmentGeneratorConfig())
    dim_seg = dim_builder.build(
        {"city": np.array(["nyc", "sf"], dtype=object),
         "state": np.array(["NY", "CA"], dtype=object)},
        str(http_cluster["tmp"] / "bd"), "cities_0")
    c.upload_segment(dim_cfg.table_name_with_type, dim_seg)

    assert _wait_until(lambda: all(
        len(node.segments_served(cfg.table_name_with_type)) == 1
        and len(node.segments_served(dim_cfg.table_name_with_type)) == 1
        for node, _, _ in http_cluster["servers"]))

    from pinot_tpu.cluster.process import BrokerClient
    from pinot_tpu.utils.metrics import get_registry
    stages_before = get_registry().counter_value("pinot_server_join_stages")
    bc = BrokerClient(http_cluster["bsvc"].url)

    # the broker's catalog mirror polls — retry until it converges (same race
    # note as test_http_cluster_query; pooled keep-alive clients are fast
    # enough to catch the mirror mid-sync)
    def join_rows():
        try:
            return bc.query(
                "SELECT c.state, SUM(t.fare) AS total FROM trips t "
                "JOIN cities c ON t.city = c.city GROUP BY c.state "
                "ORDER BY total DESC")["resultTable"]["rows"]
        except Exception:
            return None
    assert _wait_until(lambda: join_rows() == [["NY", 40.0], ["CA", 20.0]])
    resp = bc.query(
        "SELECT c.state, SUM(t.fare) AS total FROM trips t "
        "JOIN cities c ON t.city = c.city GROUP BY c.state ORDER BY total DESC")
    assert resp["resultTable"]["rows"] == [["NY", 40.0], ["CA", 20.0]]
    # the join partitions actually executed ON SERVERS over the wire (the
    # worker-mailbox dispatch), not broker-locally
    assert get_registry().counter_value("pinot_server_join_stages") \
        >= stages_before + 1


# -- real multi-process cluster ----------------------------------------------

def test_process_cluster_query_and_server_death(tmp_path):
    """Queries answered across >=2 OS processes; killing a server yields partial
    results (reference: OfflineClusterIntegrationTest + ChaosMonkey)."""
    with ProcessCluster(num_servers=2, work_dir=str(tmp_path)) as cluster:
        cluster.controller.add_schema(SCHEMA)
        cfg = TableConfig("trips")  # replication=1: a dead server loses data
        cluster.controller.add_table(cfg)
        table = cfg.table_name_with_type

        seg_dirs = [
            _build_segment(tmp_path / "b0", "trips_0",
                           ["nyc", "sf"], [10.0, 20.0], [1, 2]),
            _build_segment(tmp_path / "b1", "trips_1",
                           ["nyc", "la"], [30.0, 7.0], [3, 4]),
            _build_segment(tmp_path / "b2", "trips_2",
                           ["sf", "sf"], [5.0, 6.0], [5, 6]),
            _build_segment(tmp_path / "b3", "trips_3",
                           ["la", "nyc"], [8.0, 9.0], [7, 8]),
        ]
        for d in seg_dirs:
            cluster.controller.upload_segment(table, d)

        def all_online():
            status = cluster.controller.table_status(table)
            return status.get("segments", 0) == 4 and status.get("converged")

        assert _wait_until(all_online, timeout=30.0)

        # broker mirror may lag controller convergence — wait for full counts
        def full_count():
            try:
                return cluster.query("SELECT COUNT(*), SUM(fare) FROM trips"
                                     )["resultTable"]["rows"][0] == [8, 95.0]
            except Exception:
                return False
        assert _wait_until(full_count, timeout=30.0)
        resp = cluster.query("SELECT COUNT(*), SUM(fare) FROM trips")
        assert resp["resultTable"]["rows"][0] == [8, 95.0]
        assert resp["numServersResponded"] == resp["numServersQueried"]

        # kill one server process outright: partial results, not an error
        cluster.kill_server("server_1")
        resp = cluster.query("SELECT COUNT(*), SUM(fare) FROM trips")
        assert resp["partialResult"] is True
        count = resp["resultTable"]["rows"][0][0]
        assert 0 < count < 8

        # a retry routes around the dead server (unhealthy exclusion)
        resp2 = cluster.query("SELECT COUNT(*) FROM trips")
        assert resp2["resultTable"]["rows"][0][0] == count


def test_query_stream_selection(tmp_path):
    """Chunked streaming export (reference: gRPC streaming selection-only
    path): rows arrive in per-server batches; non-streamable shapes fall back
    to one buffered batch with identical results."""
    import numpy as np
    from pinot_tpu.cluster.broker import Broker
    from pinot_tpu.cluster.catalog import Catalog
    from pinot_tpu.cluster.controller import Controller
    from pinot_tpu.cluster.deepstore import LocalDeepStore
    from pinot_tpu.cluster.process import BrokerClient
    from pinot_tpu.cluster.remote import ControllerDeepStore, RemoteCatalog
    from pinot_tpu.cluster.server import ServerNode
    from pinot_tpu.cluster.services import (BrokerService, ControllerService,
                                            ServerService)
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.table import TableConfig
    from pinot_tpu.segment.writer import SegmentBuilder
    from conftest import wait_until

    catalog = Catalog()
    ctrl = Controller("c0", catalog, LocalDeepStore(str(tmp_path / "ds")),
                      str(tmp_path / "c"))
    csvc = ControllerService(ctrl)
    cats = [RemoteCatalog(csvc.url, poll_timeout_s=1.0)]
    node = ServerNode("server_0", cats[0], ControllerDeepStore(csvc.url),
                      str(tmp_path / "s0"))
    ssvc = ServerService(node)
    cats.append(RemoteCatalog(csvc.url, poll_timeout_s=1.0))
    bsvc = BrokerService(Broker("b0", cats[1]))
    try:
        schema = Schema("exp", [dimension("k"), metric("v", DataType.DOUBLE)])
        ctrl.add_schema(schema)
        ctrl.add_table(TableConfig("exp"))
        n = 500
        for i in range(2):
            seg = SegmentBuilder(schema).build(
                {"k": [f"k{j % 9}" for j in range(n)],
                 "v": np.arange(n, dtype=np.float64) + i},
                str(tmp_path / "b"), f"exp_{i}")
            ctrl.upload_segment("exp_OFFLINE", seg)
        bc = BrokerClient(bsvc.url)
        wait_until(lambda: bc.query("SELECT COUNT(*) FROM exp")
                   ["resultTable"]["rows"][0][0] == 2 * n)

        got_rows, cols = [], None
        for kind, payload in bc.query_stream(
                "SELECT k, v FROM exp WHERE v >= 1 LIMIT 100000"):
            if kind == "schema":
                cols = payload
            else:
                got_rows.extend(payload)
        assert cols == ["k", "v"]
        buffered = bc.query("SELECT COUNT(*) FROM exp WHERE v >= 1")
        assert len(got_rows) == buffered["resultTable"]["rows"][0][0]

        # LIMIT respected mid-stream
        limited = []
        for kind, payload in bc.query_stream("SELECT k FROM exp LIMIT 37"):
            if kind == "rows":
                limited.extend(payload)
        assert len(limited) == 37

        # non-streamable shape (aggregation): buffered fallback, same results
        agg_rows = []
        for kind, payload in bc.query_stream(
                "SELECT k, COUNT(*) FROM exp GROUP BY k ORDER BY k LIMIT 20"):
            if kind == "rows":
                agg_rows.extend(payload)
        want = bc.query("SELECT k, COUNT(*) FROM exp GROUP BY k "
                        "ORDER BY k LIMIT 20")["resultTable"]["rows"]
        assert agg_rows == want
    finally:
        for c in cats:
            c.close()
        for s in (csvc, ssvc, bsvc):
            s.stop()


def test_query_stream_errors_cleanly_on_bad_table(tmp_path):
    """A failure after the 200/chunked headers surfaces as a final error event,
    not an abrupt connection close."""
    from pinot_tpu.cluster.broker import Broker
    from pinot_tpu.cluster.catalog import Catalog
    from pinot_tpu.cluster.process import BrokerClient
    from pinot_tpu.cluster.services import BrokerService
    bsvc = BrokerService(Broker("b0", Catalog()))
    try:
        bc = BrokerClient(bsvc.url)
        with pytest.raises(RuntimeError, match="stream failed"):
            list(bc.query_stream("SELECT k FROM nosuchtable LIMIT 5"))
    finally:
        bsvc.stop()


def test_server_restart_recovers_segments(tmp_path):
    """Kill -9 a server, restart it under the same id: it re-registers,
    reloads its assigned segments from the deep store, and full (non-partial)
    results come back (reference: server restart recovery via deep-store
    download + Helix re-registration; SURVEY §5 checkpoint/resume)."""
    import numpy as np
    from pinot_tpu.cluster.process import ProcessCluster
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.segment.writer import SegmentBuilder
    from pinot_tpu.table import TableConfig
    from conftest import wait_until

    schema = Schema("rec", [dimension("k"), metric("v", DataType.DOUBLE)])
    with ProcessCluster(num_servers=1, work_dir=str(tmp_path)) as cluster:
        cluster.controller.add_schema(schema)
        cluster.controller.add_table(TableConfig("rec"))
        for i in range(2):
            seg = SegmentBuilder(schema).build(
                {"k": [f"k{j % 4}" for j in range(300)],
                 "v": np.arange(300, dtype=np.float64)},
                str(tmp_path / "b"), f"rec_{i}")
            cluster.controller.upload_segment("rec_OFFLINE", seg)
        assert wait_until(lambda: cluster.query("SELECT COUNT(*) FROM rec")
                          ["resultTable"]["rows"][0][0] == 600)

        cluster.kill_server("server_0")

        def partial_now():
            r = cluster.query("SELECT COUNT(*) FROM rec")
            return r.get("partialResult") is True
        assert wait_until(partial_now, timeout=30)

        cluster.restart_server("server_0")

        def full_again():
            r = cluster.query("SELECT COUNT(*) FROM rec")
            return (r["resultTable"]["rows"][0][0] == 600
                    and not r.get("partialResult"))
        assert wait_until(full_again, timeout=60)


def test_http_service_str_body_is_encoded_not_chunked():
    """A handler returning an unencoded str must be sent as one body, not
    chunk-iterated per character (which garbled the response)."""
    from pinot_tpu.cluster.http_service import HttpService, http_call
    svc = HttpService()
    svc.route("GET", "hello", lambda parts, params, body:
              (200, "text/plain", "hello world"))
    svc.start()
    try:
        assert http_call("GET", f"{svc.url}/hello") == b"hello world"
    finally:
        svc.stop()
