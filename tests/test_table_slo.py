"""Per-table resource accounting + SLO burn-rate verdicts.

The broker attributes every query's resources to its logical table
(`pinot_table_*` labeled gauges + the /debug tableStats panel); the
controller's SLOStatusChecker turns those rollups into multi-window
burn-rate verdicts (`sloStatus`, `pinot_controller_slo_*` gauges) — the
SRE-workbook multi-burn-rate policy over cluster data.
"""

import numpy as np
import pytest

from pinot_tpu.cluster import QuickCluster
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.table import TableConfig
from pinot_tpu.utils.metrics import get_registry


@pytest.fixture
def acct_cluster(tmp_path):
    schema = Schema("acct", [dimension("site", DataType.STRING),
                             metric("v", DataType.LONG)])
    cluster = QuickCluster(num_servers=2, work_dir=str(tmp_path))
    cfg = TableConfig("acct", replication=1)
    cluster.create_table(schema, cfg)
    cluster.ingest_columns(cfg, {
        "site": np.array(["a", "b"] * 50),
        "v": np.arange(100, dtype=np.int64),
    })
    return cluster


# -- per-table accounting -----------------------------------------------------

def test_table_rollup_gauges_and_debug_panel(acct_cluster):
    for _ in range(3):
        acct_cluster.query("SELECT site, SUM(v) FROM acct GROUP BY site")
    snap = get_registry().snapshot()
    assert snap["pinot_table_queries{table=acct}"] == 3.0
    assert snap["pinot_table_time_ms{table=acct}"] > 0
    assert snap["pinot_table_rows_scanned{table=acct}"] == 300.0
    assert snap["pinot_table_errors{table=acct}"] == 0.0
    dbg = acct_cluster.broker.debug_stats()
    panel = dbg["tableStats"]["acct"]
    assert panel["numQueries"] == 3
    assert panel["rowsScanned"] == 300
    assert panel["avgTimeMs"] > 0
    assert panel["p99LatencyMs"] > 0
    # device/bytes/queue-wait attribution columns always present (0 on the
    # pure-CPU path) so cluster_top renders a stable panel
    for key in ("deviceExecMs", "bytesFetched", "queueWaitMs",
                "numSlowQueries", "numOverSlo"):
        assert key in panel


def test_table_errors_attributed(acct_cluster):
    with pytest.raises(Exception):
        acct_cluster.query("SELECT nope_col, SUM(v) FROM acct GROUP BY nope_col")
    snap = get_registry().snapshot()
    assert snap["pinot_table_errors{table=acct}"] >= 1.0


def test_slow_and_over_slo_counted(acct_cluster):
    cat = acct_cluster.broker.catalog
    cat.put_property("clusterConfig/broker.slow.query.ms", "0")
    cat.put_property("clusterConfig/slo.latency.p99.ms", "0")
    try:
        acct_cluster.query("SELECT COUNT(*) FROM acct")
    finally:
        cat.put_property("clusterConfig/broker.slow.query.ms", None)
        cat.put_property("clusterConfig/slo.latency.p99.ms", None)
    panel = acct_cluster.broker.debug_stats()["tableStats"]["acct"]
    assert panel["numSlowQueries"] >= 1
    assert panel["numOverSlo"] >= 1


def test_dropped_table_series_removed(acct_cluster):
    acct_cluster.query("SELECT COUNT(*) FROM acct")
    assert "pinot_table_queries{table=acct}" in get_registry().snapshot()
    acct_cluster.controller.drop_table("acct_OFFLINE")
    # /debug forces the sweep: rollup + every labeled series must go
    dbg = acct_cluster.broker.debug_stats()
    assert "acct" not in dbg["tableStats"]
    snap = get_registry().snapshot()
    assert not any(k.startswith("pinot_table_") and "table=acct}" in k
                   for k in snap), sorted(
        k for k in snap if "table=acct}" in k)


# -- SLO burn-rate verdicts ---------------------------------------------------

@pytest.fixture
def slo_controller(tmp_path):
    from pinot_tpu.cluster.catalog import Catalog
    from pinot_tpu.cluster.controller import Controller
    from pinot_tpu.cluster.deepstore import LocalDeepStore
    catalog = Catalog()
    controller = Controller("controller_slo", catalog,
                            LocalDeepStore(str(tmp_path / "ds")),
                            str(tmp_path / "ctrl"))
    schema = Schema("sloq", [dimension("k", DataType.STRING)])
    controller.add_schema(schema)
    controller.add_table(TableConfig("sloq", replication=1))
    catalog.put_property("clusterConfig/slo.latency.p99.ms", "100")
    catalog.put_property("clusterConfig/slo.error.rate", "0.01")
    return controller


def _poller(counters):
    return lambda: {"tableStats": {"sloq": dict(counters)}}


def test_slo_burn_rate_escalation(slo_controller):
    """Synthetic counter timeline drives HEALTHY -> DEGRADED (fast & slow
    burn > 1) -> UNHEALTHY (fast burn >= the 14.4x page threshold)."""
    c = slo_controller
    counters = {"numQueries": 1000, "numErrors": 0, "numOverSlo": 0}
    c.slo_pollers["b1"] = _poller(counters)

    # first observation: no prior sample in any window -> zero burn
    assert c.run_slo_check(now=1000.0) == {"sloq": "HEALTHY"}
    st = c.slo_status("sloq")
    assert st["burnRates"] == {"errorFast": 0.0, "errorSlow": 0.0,
                               "latencyFast": 0.0, "latencySlow": 0.0}
    assert st["latencyTargetMs"] == 100.0 and st["errorRateTarget"] == 0.01

    # clean traffic: burns stay zero
    counters.update(numQueries=2000)
    assert c.run_slo_check(now=1060.0) == {"sloq": "HEALTHY"}

    # 2% errors over the window = 2x the 1% budget in BOTH windows -> DEGRADED
    counters.update(numQueries=3000, numErrors=40)
    assert c.run_slo_check(now=1120.0) == {"sloq": "DEGRADED"}
    st = c.slo_status("sloq")
    assert st["burnRates"]["errorFast"] == 2.0
    assert st["burnRates"]["errorSlow"] == 2.0
    assert any("error burn rate" in r for r in st["reasons"])

    # error spike: 18% errors over the fast window >= 14.4x -> UNHEALTHY
    counters.update(numQueries=4000, numErrors=540)
    assert c.run_slo_check(now=1180.0) == {"sloq": "UNHEALTHY"}
    st = c.slo_status("sloq")
    assert st["burnRates"]["errorFast"] >= c.SLO_PAGE_BURN_RATE
    assert any("budget burning" in r for r in st["reasons"])

    snap = get_registry().snapshot()
    assert snap["pinot_controller_slo_healthy{table=sloq}"] == 0.0
    assert snap["pinot_controller_slo_error_burn_rate{table=sloq}"] >= 14.4


def test_slo_latency_burn_via_over_slo_counter(slo_controller):
    c = slo_controller
    counters = {"numQueries": 1000, "numErrors": 0, "numOverSlo": 0}
    c.slo_pollers["b1"] = _poller(counters)
    c.run_slo_check(now=2000.0)
    # 5% of window queries broke the p99 target = 5x the 1% violation budget
    counters.update(numQueries=2000, numOverSlo=50)
    assert c.run_slo_check(now=2060.0) == {"sloq": "DEGRADED"}
    st = c.slo_status("sloq")
    assert st["burnRates"]["latencyFast"] == 5.0
    snap = get_registry().snapshot()
    assert snap["pinot_controller_slo_latency_burn_rate{table=sloq}"] == 5.0


def test_slo_unreachable_broker_degrades(slo_controller):
    c = slo_controller

    def boom():
        raise ConnectionError("broker down")

    counters = {"numQueries": 100, "numErrors": 0, "numOverSlo": 0}
    c.slo_pollers["b1"] = _poller(counters)
    c.slo_pollers["b2"] = boom
    assert c.run_slo_check(now=3000.0) == {"sloq": "DEGRADED"}
    st = c.slo_status("sloq")
    assert st["unreachableBrokers"] == ["b2"]


def test_slo_stale_table_series_removed(slo_controller):
    c = slo_controller
    counters = {"numQueries": 100, "numErrors": 0, "numOverSlo": 0}
    c.slo_pollers["b1"] = _poller(counters)
    c.run_slo_check(now=4000.0)
    assert "pinot_controller_slo_healthy{table=sloq}" in \
        get_registry().snapshot()
    # the table stops reporting (dropped): verdict + gauges must clear
    c.slo_pollers["b1"] = lambda: {"tableStats": {}}
    assert c.run_slo_check(now=4060.0) == {}
    snap = get_registry().snapshot()
    assert not any("table=sloq}" in k and "slo" in k for k in snap)
    with_type = c.slo_status("sloq")
    assert with_type["sloState"] == "HEALTHY"       # known but unjudged
    assert "no query traffic" in with_type["message"]


def test_slo_unconfigured_tears_down(slo_controller):
    c = slo_controller
    counters = {"numQueries": 100, "numErrors": 50, "numOverSlo": 0}
    c.slo_pollers["b1"] = _poller(counters)
    c.run_slo_check(now=5000.0)
    # remove both targets: the whole plane tears down on the next tick
    c.catalog.put_property("clusterConfig/slo.latency.p99.ms", None)
    c.catalog.put_property("clusterConfig/slo.error.rate", None)
    assert c.run_slo_check(now=5060.0) == {}
    assert not any("pinot_controller_slo" in k and "table=sloq}" in k
                   for k in get_registry().snapshot())
    st = c.slo_status("sloq")
    assert st["sloState"] == "UNCONFIGURED"
    assert "no SLO targets" in st["message"]


def test_slo_status_accepts_name_with_type_and_404s_unknown(slo_controller):
    c = slo_controller
    counters = {"numQueries": 100, "numErrors": 0, "numOverSlo": 0}
    c.slo_pollers["b1"] = _poller(counters)
    c.run_slo_check(now=6000.0)
    # rollups key the LOGICAL name; the REST path uses nameWithType
    assert c.slo_status("sloq_OFFLINE")["table"] == "sloq"
    with pytest.raises(ValueError):
        c.slo_status("never_heard_of_it")


def test_slo_status_http_route(slo_controller):
    from pinot_tpu.cluster.http_service import HttpError, get_json
    from pinot_tpu.cluster.services import ControllerService
    c = slo_controller
    counters = {"numQueries": 200, "numErrors": 0, "numOverSlo": 0}
    c.slo_pollers["b1"] = _poller(counters)
    c.run_slo_check(now=7000.0)
    svc = ControllerService(c)
    try:
        body = get_json(f"{svc.url}/tables/sloq_OFFLINE/sloStatus")
        assert body["sloState"] == "HEALTHY"
        assert body["table"] == "sloq"
        with pytest.raises(HttpError):
            get_json(f"{svc.url}/tables/ghost/sloStatus")
        # the controller /debug rollup carries the verdict map too
        dbg = get_json(f"{svc.url}/debug")
        assert dbg["sloStatus"]["sloq"]["sloState"] == "HEALTHY"
        assert "SLOStatusChecker" in dbg["periodicTasks"]
    finally:
        svc.stop()


# -- cluster_top: SLO column + top-consumers panel ----------------------------

def test_cluster_top_renders_slo_and_consumers():
    from pinot_tpu.tools.cluster_top import render, snapshot

    pages = {
        "http://c:9000/tables": {"tables": ["trips_REALTIME"]},
        "http://c:9000/tables/trips_REALTIME/ingestionStatus": {
            "table": "trips_REALTIME", "ingestionState": "HEALTHY",
            "numConsumingSegments": 2, "maxOffsetLag": 0,
            "maxFreshnessLagMs": 1200.0, "totalRowsPerSecond": 42.0,
            "reasons": []},
        "http://c:9000/tables/trips_REALTIME/sloStatus": {
            "table": "trips", "sloState": "DEGRADED",
            "reasons": ["error burn rate 2x fast / 2x slow — "
                        "budget exhausting"]},
        "http://c:9000/debug": {"periodicTasks": {}},
        "http://b:8099/debug": {
            "queryStats": {"numQueries": 7, "avgTimeMs": 3.0,
                           "numSlowQueries": 1},
            "tableStats": {
                "trips": {"numQueries": 7, "deviceExecMs": 12.5,
                          "queueWaitMs": 1.25, "bytesFetched": 4096,
                          "rowsScanned": 700, "p99LatencyMs": 9.5,
                          "numSlowQueries": 1, "numErrors": 0}}},
    }
    snap = snapshot("http://c:9000", "http://b:8099", lambda url: pages[url])
    assert snap["slo"]["trips_REALTIME"]["sloState"] == "DEGRADED"
    text = render(snap)
    row = next(line for line in text.splitlines()
               if line.startswith("trips_REALTIME"))
    assert "DEGRADED" in row
    assert "error burn rate" in row
    assert "top consumers" in text
    consumer_row = next(line for line in text.splitlines()
                        if line.startswith("trips "))
    assert "4096" in consumer_row and "700" in consumer_row


def test_cluster_top_tolerates_missing_slo_endpoint():
    from pinot_tpu.tools.cluster_top import render, snapshot

    def fetch(url):
        if url.endswith("/tables"):
            return {"tables": ["t1_OFFLINE"]}
        if url.endswith("/ingestionStatus"):
            return {"table": "t1_OFFLINE", "ingestionState": "HEALTHY",
                    "reasons": []}
        raise ConnectionError("older controller")

    snap = snapshot("http://c:9000", None, fetch)
    text = render(snap)
    row = next(line for line in text.splitlines()
               if line.startswith("t1_OFFLINE"))
    assert " - " in row        # SLO column degrades to "-"
