"""Tiered-storage lifecycle tests: HBM admission gate, pressure eviction,
host-tier degradation, cold demotion + lazy reload, deep-store download
retry/quarantine, and the unload-vs-in-flight-query deferred-release fix.

The tier ladder under test (cluster/tiering.py):

* hot  — ledger-accounted device blocks, bounded by
         `capacity * (1 - server.hbm.target.headroom.pct/100)`
* warm — host-RAM readers; an evicted/rejected segment answers on the host
         plan (`segmentsServedHostTier`), never with short rows
* cold — deep store only; a COLD-assigned segment stays routable and the
         first query lazily re-downloads it within its deadline budget

Every scenario pins the process ledger's capacity explicitly
(`set_capacity`) and restores a fresh ledger afterwards — capacity is
process-global state and must not leak between tests.
"""

import threading
import time

import numpy as np
import pytest

from pinot_tpu.cluster import QuickCluster
from pinot_tpu.engine import datablock
from pinot_tpu.engine.datablock import (block_for, has_block,
                                        predicted_block_bytes, release_block)
from pinot_tpu.table import TableConfig
from pinot_tpu.utils import faults
from pinot_tpu.utils.faults import FaultSchedule
from pinot_tpu.utils.memledger import get_ledger, reset_ledger
from pinot_tpu.utils.metrics import get_registry

from conftest import make_ssb_columns

ROWS_PER_SEGMENT = 2000


def _counter_value(name, **labels):
    """One counter/gauge out of the registry snapshot by name + label pairs
    (label render order is an implementation detail)."""
    for key, v in get_registry().snapshot().items():
        if key == name:
            return v
        if key.startswith(name + "{") and all(
                f"{lk}={lv}" in key for lk, lv in labels.items()):
            return v
    return None


def _build_cluster(tmp_path, ssb_schema, num_segments, seed=11):
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    cfg = TableConfig(ssb_schema.name, replication=1,
                      time_column="lo_orderdate")
    cluster.create_table(ssb_schema, cfg)
    rng = np.random.default_rng(seed)
    names = [cluster.ingest_columns(cfg, make_ssb_columns(rng,
                                                          ROWS_PER_SEGMENT))
             for _ in range(num_segments)]
    return cluster, cfg, names


@pytest.fixture()
def fresh_ledger():
    """Isolate the process-global ledger + metrics registry: tests in this
    module pin tiny capacities that must not leak into other modules."""
    reset_ledger()
    get_registry().reset()
    faults.deactivate()
    from pinot_tpu.cluster.peers import clear_download_quarantine
    clear_download_quarantine()
    yield get_ledger()
    faults.deactivate()
    clear_download_quarantine()
    reset_ledger()
    get_registry().reset()


# -- deferred release: unload never races an in-flight query ------------------

def test_remove_segment_defers_block_drop_until_refcount_drains(
        tmp_path, ssb_schema, fresh_ledger):
    """The satellite race fix, threaded: a query thread holds acquired
    segment handles and keeps executing while the main thread unloads the
    segment. Every execution must see the full row count — the device block
    and ledger entries survive until the LAST release drains the refcount."""
    from pinot_tpu.query.context import compile_query
    cluster, cfg, names = _build_cluster(tmp_path, ssb_schema, 1)
    table = cfg.table_name_with_type
    server = cluster.servers[0]
    mgr = server.tables[table]
    ctx = compile_query("SELECT COUNT(*) FROM lineorder", ssb_schema)

    held = mgr.acquire([names[0]])
    assert len(held) == 1 and mgr.refcount(names[0]) == 1
    seg = held[0]
    blk = block_for(seg)    # stage device arrays the race would drop
    blk.valid
    blk.ids("lo_region")
    assert get_ledger().resident_bytes(segment=seg.name) > 0

    removed = threading.Event()
    counts = []

    def query_loop():
        for i in range(40):
            if i == 10:
                removed.wait(timeout=10.0)   # unload happens mid-stream
            res = server.executor.execute_segment(ctx, seg, None)
            counts.append(res.scalar[0] if res.scalar else None)

    t = threading.Thread(target=query_loop)
    t.start()
    mgr.remove_segment(names[0])    # in-flight refs: must defer, not drop
    removed.set()
    t.join(timeout=30.0)
    assert not t.is_alive()

    # the unload took effect for NEW queries...
    assert names[0] not in mgr.segment_names
    assert mgr.acquire([names[0]]) == []
    # ...but the in-flight holder kept its device block the whole time
    assert has_block(seg)
    assert get_ledger().resident_bytes(segment=seg.name) > 0
    assert counts == [ROWS_PER_SEGMENT] * 40, "a query saw short rows"

    mgr.release(held)               # refcount drains -> deferred drop fires
    assert not has_block(seg)
    assert get_ledger().resident_bytes(segment=seg.name) == 0


# -- admission gate + host-tier degradation -----------------------------------

def test_admission_gate_rejects_past_target_and_host_tier_answers(
        tmp_path, ssb_schema, fresh_ledger):
    """Capacity sized for ~one block out of three: the query still returns
    the full (non-partial) answer, rejected segments ride the host plan
    (`segmentsServedHostTier`), and residency never exceeds capacity."""
    cluster, cfg, names = _build_cluster(tmp_path, ssb_schema, 3)
    table = cfg.table_name_with_type
    server = cluster.servers[0]
    mgr = server.tables[table]
    predicted = predicted_block_bytes(mgr.get(names[0]))
    assert predicted > 0
    capacity = int(predicted * 1.5)      # target = 0.9*cap ~= 1.35 blocks
    get_ledger().set_capacity(capacity)

    res = cluster.query("SELECT COUNT(*) FROM lineorder")
    assert res.rows[0][0] == 3 * ROWS_PER_SEGMENT
    assert not res.stats["partialResult"]
    assert res.stats["segmentsServedHostTier"] >= 1
    assert get_ledger().snapshot()["totalBytes"] <= capacity

    tiering = server.tiering.snapshot()
    assert tiering["rejections"] >= 1
    assert tiering["targetBytes"] == int(capacity * 0.9)
    assert _counter_value("pinot_server_hbm_admission_rejects",
                          table=table) >= 1


def test_admission_reservations_prevent_same_query_overcommit(
        tmp_path, ssb_schema, fresh_ledger):
    """All of a query's segments admit BEFORE any block stages; without
    in-flight reservations the gate would admit every segment against an
    empty ledger and overshoot. With them, one query over 3 segments stays
    under capacity."""
    cluster, cfg, names = _build_cluster(tmp_path, ssb_schema, 3)
    server = cluster.servers[0]
    mgr = server.tables[cfg.table_name_with_type]
    predicted = predicted_block_bytes(mgr.get(names[0]))
    capacity = int(predicted * 1.5)
    get_ledger().set_capacity(capacity)

    # first-ever query: ledger empty, all three admissions race the stage
    res = cluster.query("SELECT SUM(lo_revenue) FROM lineorder")
    assert res.rows[0][0] is not None
    assert get_ledger().snapshot()["totalBytes"] <= capacity
    staged = sum(1 for n in names if has_block(mgr.get(n)))
    assert staged <= 1, "reservations failed: multiple blocks staged"


def test_pressure_sweep_evicts_cold_blocks_but_never_inflight(
        tmp_path, ssb_schema, fresh_ledger):
    """The periodic pressure loop walks residency back under target by
    bytes*coldness score — but a segment acquired by an in-flight query is
    never a victim; its eviction waits for the refcount to drain."""
    cluster, cfg, names = _build_cluster(tmp_path, ssb_schema, 2)
    table = cfg.table_name_with_type
    server = cluster.servers[0]
    mgr = server.tables[table]

    # big capacity: both segments admit + stage
    cluster.query("SELECT SUM(lo_revenue) FROM lineorder")
    assert all(has_block(mgr.get(n)) for n in names)
    resident = get_ledger().resident_bytes()
    assert resident > 0

    held = mgr.acquire([names[0]])   # an in-flight query holds segment 0
    get_ledger().set_capacity(max(1, resident // 4))   # force pressure
    evicted = server.tiering.run_pressure_sweep()
    assert evicted >= 1
    assert has_block(mgr.get(names[0])), "evicted a block under a live query"
    assert not has_block(mgr.get(names[1]))
    assert _counter_value("pinot_server_hbm_evictions") >= 1

    mgr.release(held)                # refcount drained: now evictable
    assert server.tiering.run_pressure_sweep() >= 1
    assert not has_block(mgr.get(names[0]))
    assert get_ledger().resident_bytes() <= server.tiering.target_bytes()


def test_hot_and_host_tier_answers_are_identical(tmp_path, ssb_schema,
                                                 fresh_ledger):
    """Differential suite: the same queries over the same data must return
    identical rows whether every segment rides the device plan (unconstrained
    capacity) or admission forces most onto the host plan (pinned capacity
    with eviction cycling between queries)."""
    suite = [
        "SELECT COUNT(*) FROM lineorder",
        "SELECT SUM(lo_revenue), MIN(lo_quantity), MAX(lo_discount) "
        "FROM lineorder",
        "SELECT lo_region, SUM(lo_revenue) FROM lineorder "
        "GROUP BY lo_region ORDER BY lo_region LIMIT 20",
        "SELECT COUNT(*) FROM lineorder WHERE lo_quantity > 25",
        "SELECT lo_category, COUNT(*) FROM lineorder "
        "WHERE lo_region = 'ASIA' GROUP BY lo_category "
        "ORDER BY lo_category LIMIT 20",
    ]

    def run(workdir, capacity_blocks):
        reset_ledger()
        cluster, cfg, names = _build_cluster(workdir, ssb_schema, 3, seed=23)
        mgr = cluster.servers[0].tables[cfg.table_name_with_type]
        predicted = predicted_block_bytes(mgr.get(names[0]))
        get_ledger().set_capacity(int(predicted * capacity_blocks))
        rows, host_served = [], 0
        for _ in range(2):           # two passes: evict/promote churn
            for sql in suite:
                res = cluster.query(sql)
                assert not res.stats["partialResult"]
                rows.append(res.rows)
                host_served += res.stats.get("segmentsServedHostTier", 0)
        return rows, host_served

    hot_rows, hot_host = run(tmp_path / "hot", capacity_blocks=100.0)
    tiered_rows, tiered_host = run(tmp_path / "tiered", capacity_blocks=1.5)
    assert hot_host == 0
    assert tiered_host > 0, "constrained run never exercised the host tier"
    # float aggregates accumulate in different precisions on the two plans
    # (device f32 reductions vs host f64) — identical up to rounding
    assert len(hot_rows) == len(tiered_rows)
    for hot_res, tiered_res in zip(hot_rows, tiered_rows):
        assert len(hot_res) == len(tiered_res)
        for hot_row, tiered_row in zip(hot_res, tiered_res):
            assert len(hot_row) == len(tiered_row)
            for hot_cell, tiered_cell in zip(hot_row, tiered_row):
                if isinstance(hot_cell, float):
                    assert tiered_cell == pytest.approx(hot_cell, rel=1e-6)
                else:
                    assert tiered_cell == hot_cell


def test_4x_capacity_table_serves_full_suite_without_oom(
        tmp_path, ssb_schema, fresh_ledger):
    """The tentpole acceptance: a table ~4x the pinned HBM capacity serves
    the full query suite with residency <= capacity after every query and in
    the ledger's watermark history (modulo transient scratch, which the
    watermark includes by design)."""
    cluster, cfg, names = _build_cluster(tmp_path, ssb_schema, 5)
    mgr = cluster.servers[0].tables[cfg.table_name_with_type]
    predicted = predicted_block_bytes(mgr.get(names[0]))
    capacity = int(predicted * 1.25)     # 5 blocks / 1.25 = 4x capacity
    get_ledger().set_capacity(capacity)

    suite = [
        "SELECT COUNT(*) FROM lineorder",
        "SELECT SUM(lo_revenue) FROM lineorder",
        "SELECT lo_region, COUNT(*) FROM lineorder GROUP BY lo_region "
        "ORDER BY lo_region LIMIT 10",
        "SELECT COUNT(*) FROM lineorder WHERE lo_discount >= 5",
    ]
    for round_ in range(2):
        for sql in suite:
            res = cluster.query(sql)
            assert not res.stats["partialResult"], sql
            snap = get_ledger().snapshot()
            assert snap["totalBytes"] <= capacity, \
                f"resident {snap['totalBytes']} > capacity {capacity}: {sql}"
    assert cluster.query(
        "SELECT COUNT(*) FROM lineorder").rows[0][0] == 5 * ROWS_PER_SEGMENT

    snap = get_ledger().snapshot()
    transient = snap["transientPeakBytes"]
    # the watermark is the peak of resident + transient scratch: residency
    # itself never passed capacity (the history ring samples on an interval
    # and may be empty in a fast test — the scalar peak always updates)
    assert snap["watermarkBytes"] <= capacity + transient
    for _, footprint in snap["watermarkHistory"]:
        assert footprint <= capacity + transient
    # the gate was actually exercised, not vacuously satisfied
    tiering = cluster.servers[0].tiering.snapshot()
    assert tiering["rejections"] + tiering["evictions"] > 0


# -- capacity knob ------------------------------------------------------------

def test_capacity_knob_overrides_probe_on_server_start(tmp_path,
                                                       fresh_ledger):
    """`server.hbm.capacity.bytes` replaces the probed/estimated capacity at
    server construction and marks it exact."""
    from pinot_tpu.cluster.catalog import Catalog
    from pinot_tpu.cluster.deepstore import LocalDeepStore
    from pinot_tpu.cluster.server import ServerNode
    catalog = Catalog()
    catalog.put_property("clusterConfig/server.hbm.capacity.bytes", "123456")
    server = ServerNode("server_knob", catalog,
                        LocalDeepStore(str(tmp_path / "ds")),
                        str(tmp_path / "data"))
    try:
        assert get_ledger().capacity_bytes() == (123456, False)
        assert get_ledger().snapshot()["capacityBytes"] == 123456
    finally:
        server.shutdown()


def test_malformed_capacity_knob_keeps_probed_value(tmp_path, fresh_ledger):
    from pinot_tpu.cluster.catalog import Catalog
    from pinot_tpu.cluster.deepstore import LocalDeepStore
    from pinot_tpu.cluster.server import ServerNode
    before = get_ledger().capacity_bytes()
    catalog = Catalog()
    catalog.put_property("clusterConfig/server.hbm.capacity.bytes",
                         "not-a-number")
    server = ServerNode("server_knob2", catalog,
                        LocalDeepStore(str(tmp_path / "ds")),
                        str(tmp_path / "data"))
    try:
        assert get_ledger().capacity_bytes() == before
    finally:
        server.shutdown()


# -- cold tier: demotion, lazy reload, deadline bound -------------------------

def test_cold_demotion_unloads_and_first_query_lazily_reloads(
        tmp_path, ssb_schema, fresh_ledger):
    cluster, cfg, names = _build_cluster(tmp_path, ssb_schema, 2)
    table = cfg.table_name_with_type
    server = cluster.servers[0]
    mgr = server.tables[table]
    assert cluster.query(
        "SELECT COUNT(*) FROM lineorder").rows[0][0] == 2 * ROWS_PER_SEGMENT

    assert cluster.controller.demote_segment_to_cold(table, names[0])
    # catalog notify is synchronous: the server reconciled inline
    from pinot_tpu.cluster.catalog import COLD
    assert cluster.catalog.external_view[table][names[0]] \
        == {"server_0": COLD}
    assert names[0] not in mgr.segment_names
    assert server.local_segment_dir(table, names[0]) is None
    assert get_ledger().resident_bytes(segment=names[0]) == 0
    assert _counter_value("pinot_controller_cold_demotions", table=table) == 1
    # re-demoting an already-cold segment is a no-op, not a double count
    assert not cluster.controller.demote_segment_to_cold(table, names[0])

    # COLD stays routable: the next query lazily downloads + answers in full
    res = cluster.query("SELECT COUNT(*) FROM lineorder")
    assert res.rows[0][0] == 2 * ROWS_PER_SEGMENT
    assert not res.stats["partialResult"]
    assert res.stats["segmentsColdLoaded"] == 1
    assert res.stats["coldLoadMs"] > 0
    assert server.tiering.snapshot()["coldLoads"] == 1
    assert _counter_value("pinot_server_hbm_cold_loads") == 1

    # the lazily loaded copy STAYS loaded (reconcile must not tear it down:
    # eviction is the tiering manager's call, not the reconciler's)
    server.reconcile(table)
    assert names[0] in mgr.segment_names
    res = cluster.query("SELECT COUNT(*) FROM lineorder")
    assert res.stats.get("segmentsColdLoaded", 0) == 0
    assert server.tiering.snapshot()["coldLoads"] == 1


def test_cold_load_past_deadline_fails_typed(tmp_path, ssb_schema,
                                             fresh_ledger):
    """A query whose budget is already spent must fail with a typed
    QueryTimeoutError BEFORE burning a deep-store download, naming the
    cold-tier load it refused."""
    from pinot_tpu.query.context import compile_query
    from pinot_tpu.query.scheduler import QueryTimeoutError
    cluster, cfg, names = _build_cluster(tmp_path, ssb_schema, 1)
    table = cfg.table_name_with_type
    server = cluster.servers[0]
    assert cluster.controller.demote_segment_to_cold(table, names[0])
    assert names[0] not in server.tables[table].segment_names

    ctx = compile_query("SELECT COUNT(*) FROM lineorder", ssb_schema)
    ctx.options["deadlineEpochMs"] = time.time() * 1000 - 1000
    with pytest.raises(QueryTimeoutError) as exc:
        server._execute_partial(table, ctx, [names[0]])
    assert "cold-tier load" in str(exc.value)
    # the refusal left nothing half-loaded
    assert names[0] not in server.tables[table].segment_names


# -- deep-store download faults: retry, quarantine ----------------------------

def test_download_retry_absorbs_transient_faults(tmp_path, ssb_schema,
                                                 fresh_ledger):
    """Two injected download failures < the default 3-attempt budget: the
    cold reload succeeds on the final attempt and the retries are counted."""
    cluster, cfg, names = _build_cluster(tmp_path, ssb_schema, 2)
    table = cfg.table_name_with_type
    assert cluster.controller.demote_segment_to_cold(table, names[0])

    sched = FaultSchedule({"deepstore.download.fail": {"p": 1.0, "count": 2}},
                          seed=3)
    with faults.active(sched):
        res = cluster.query("SELECT COUNT(*) FROM lineorder")
    assert sched.fired("deepstore.download.fail") == 2, \
        "the schedule never fired: the retry path was not exercised"
    assert res.rows[0][0] == 2 * ROWS_PER_SEGMENT
    assert not res.stats["partialResult"]
    assert _counter_value("pinot_deepstore_download_retries") >= 2


def test_download_exhaustion_quarantines_then_recovers(tmp_path, ssb_schema,
                                                       fresh_ledger):
    """Faults beyond the retry budget: the blob is quarantined (later
    fetches skip the backoff), the query outcome is typed or flagged —
    never silent short rows — and clearing the quarantine after the store
    recovers restores full answers."""
    from pinot_tpu.cluster.peers import clear_download_quarantine
    cluster, cfg, names = _build_cluster(tmp_path, ssb_schema, 2)
    table = cfg.table_name_with_type
    assert cluster.controller.demote_segment_to_cold(table, names[0])

    sched = FaultSchedule({"deepstore.download.fail": {"p": 1.0, "count": 50}},
                          seed=5)
    with faults.active(sched):
        try:
            res = cluster.query("SELECT COUNT(*) FROM lineorder")
        except Exception as e:
            outcome = f"error:{type(e).__name__}"
        else:
            assert res.stats["partialResult"], \
                f"silent short rows: {res.rows} without partialResult"
            outcome = "partial"
    assert sched.fired("deepstore.download.fail") >= 3
    assert outcome in ("partial", "error:ConnectionError",
                       "error:QueryScatterError", "error:RuntimeError")
    assert _counter_value("pinot_deepstore_download_quarantined") >= 1

    # store healthy again, but the blob is quarantined: deep store is still
    # skipped (and the only replica is COLD, so no peer can serve it). The
    # broker marked the erroring server unhealthy — re-admit it first, the
    # way the chaos scenarios model the operator/detector recovery.
    cluster.revive_server("server_0")
    cluster.broker.failure_detector.notify_healthy("server_0")
    try:
        res = cluster.query("SELECT COUNT(*) FROM lineorder")
        still_degraded = res.stats["partialResult"]
    except Exception:
        still_degraded = True
    assert still_degraded, "quarantine did not stick"

    clear_download_quarantine()      # operator re-admits the blob
    cluster.revive_server("server_0")
    cluster.broker.failure_detector.notify_healthy("server_0")
    res = cluster.query("SELECT COUNT(*) FROM lineorder")
    assert res.rows[0][0] == 2 * ROWS_PER_SEGMENT
    assert not res.stats["partialResult"]


# -- controller planes: retention demotion, memoryStatus rollup ---------------

def test_retention_demotes_to_cold_instead_of_deleting(tmp_path, ssb_schema,
                                                       fresh_ledger):
    cluster, cfg, names = _build_cluster(tmp_path, ssb_schema, 2)
    table = cfg.table_name_with_type
    cfg.retention_days = 1.0
    cluster.catalog.put_table_config(cfg)
    metas = cluster.catalog.segments[table]
    assert all(metas[n].end_time_ms is not None for n in names)
    future = max(m.end_time_ms for m in metas.values()) \
        + 2 * 24 * 3600 * 1000

    cluster.catalog.put_property(
        "clusterConfig/controller.retention.cold.demote", "true")
    acted = cluster.controller.run_retention(now_ms=future)
    assert sorted(acted) == sorted(f"cold:{table}/{n}" for n in names)
    # demoted, NOT deleted: metadata + deep-store copy survive, and the
    # table still answers in full via lazy cold reloads
    assert set(cluster.catalog.segments[table]) == set(names)
    res = cluster.query("SELECT COUNT(*) FROM lineorder")
    assert res.rows[0][0] == 2 * ROWS_PER_SEGMENT
    assert res.stats["segmentsColdLoaded"] == 2
    # a second pass finds everything already cold: nothing more to do
    assert cluster.controller.run_retention(now_ms=future) == []


def test_memory_status_carries_tiering_rollup(tmp_path, ssb_schema,
                                              fresh_ledger):
    cluster, cfg, names = _build_cluster(tmp_path, ssb_schema, 3)
    table = cfg.table_name_with_type
    mgr = cluster.servers[0].tables[table]
    predicted = predicted_block_bytes(mgr.get(names[0]))
    get_ledger().set_capacity(int(predicted * 1.5))
    cluster.query("SELECT COUNT(*) FROM lineorder")

    verdicts = cluster.controller.run_memory_check()
    assert verdicts[table] in ("HEALTHY", "DEGRADED", "UNHEALTHY")
    st = cluster.controller.memory_status(table)
    tiering = st["tiering"]
    assert tiering["admissions"] >= 1
    assert tiering["rejections"] >= 1
    # the cluster_top memory panel renders the same rollup
    from pinot_tpu.tools import cluster_top
    text = cluster_top.render({
        "tables": {}, "memory": {table: st}, "slo": {}})
    assert "tiering:" in text and "rejections=" in text
