"""Trigram FST-analog regex index (reference: native FST index under
segment/local/utils/nativefst/ + FSTBasedRegexpPredicateEvaluatorFactory).

Core invariant: the index must be a pure accelerator — REGEXP_LIKE results with
and without the index are identical for ANY pattern (false positives filtered by
the exact regex; candidate extraction conservative enough to never lose a match).
"""

import re
import string

import numpy as np
import pytest

from pinot_tpu.query.executor import execute_query
from pinot_tpu.segment import SegmentBuilder, SegmentGeneratorConfig, load_segment
from pinot_tpu.segment.indexes.fst import (FstIndexReader, create_fst_index,
                                           ids_matching_regex_indexed,
                                           required_literals)


# -- literal extraction ------------------------------------------------------

@pytest.mark.parametrize("pattern,expected", [
    ("error", ["error"]),
    ("^error$", ["error"]),
    ("foo.*bar", ["foo", "bar"]),
    ("ab", []),                       # too short
    ("foo|bar", []),                  # alternation voids requirements
    ("fooo*", ["foo"]),               # trailing o optional, 'foo' still required
    ("colou?r", ["colo"]),            # optional u cuts the run after 'colo'
    ("err[0-9]+code", ["err", "code"]),
    ("(warn)+fatal", ["fatal"]),
    ("a{2,3}bcd", ["bcd"]),
    ("abc\\d+", ["abc"]),
    ("(?i)error", []),                # inline flags -> not indexable
])
def test_required_literals(pattern, expected):
    assert required_literals(pattern) == expected, pattern


def _check_extraction_safe(pattern, values):
    """Every literal claimed 'required' must appear in every matching value."""
    rx = re.compile(pattern)
    for lit in required_literals(pattern):
        for v in values:
            if rx.search(v):
                assert lit in v, (pattern, lit, v)


def test_extraction_never_loses_matches_random():
    rng = np.random.default_rng(7)
    alphabet = "abcde"
    values = ["".join(rng.choice(list(alphabet), size=rng.integers(3, 12)))
              for _ in range(300)]
    pieces = ["abc", "de", "a.c", "b+", "c*", "d?e", "[ab]", "(cd)", "ab|cd",
              "^ab", "de$", "a{2}", "b{0,2}"]
    for _ in range(200):
        k = rng.integers(1, 4)
        pattern = "".join(rng.choice(pieces) for _ in range(k))
        try:
            re.compile(pattern)
        except re.error:
            continue
        _check_extraction_safe(pattern, values)


# -- index correctness vs full scan ------------------------------------------

def test_indexed_regex_equals_full_scan(tmp_path):
    rng = np.random.default_rng(3)
    words = ["server", "service", "serial", "verse", "obverse", "nurse",
             "错误代码", "err_500", "err_404", "warning", "fatal_error",
             "x" * 50, "", "abcabcabc"]
    vals = sorted({w + str(i % 7) for i, w in enumerate(words * 10)})
    path = str(tmp_path / "t.fst.npz")
    create_fst_index(path, vals)
    idx = FstIndexReader(path)
    for pattern in ["err", "err_[0-9]+", "serv(er|ice)", "^obv", "verse[0-9]$",
                    "abcabc", "错误", "nomatchxyz", "fatal_error[0-3]"]:
        got = ids_matching_regex_indexed(idx, vals, pattern)
        rx = re.compile(pattern)
        want = [i for i, v in enumerate(vals) if rx.search(v)]
        if got is None:
            continue  # unindexable pattern: full scan path, nothing to compare
        assert got.tolist() == want, pattern


def test_index_skips_unindexable_patterns(tmp_path):
    path = str(tmp_path / "u.fst.npz")
    create_fst_index(path, ["aa", "bb"])
    idx = FstIndexReader(path)
    assert idx.candidate_ids("a|b") is None
    assert idx.candidate_ids("x?y?") is None
    assert ids_matching_regex_indexed(idx, ["aa", "bb"], "a|b") is None


# -- end-to-end query path ---------------------------------------------------

@pytest.fixture(scope="module")
def fst_segment(tmp_path_factory):
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    rng = np.random.default_rng(5)
    schema = Schema("logs", [dimension("msg", DataType.STRING),
                             metric("n", DataType.INT)])
    stems = ["connection reset", "timeout waiting", "auth failed",
             "disk full", "retry scheduled", "ok"]
    msgs = [f"{stems[i % len(stems)]} host{i % 17}" for i in range(2000)]
    cols = {"msg": msgs, "n": rng.integers(0, 100, 2000, dtype=np.int32)}
    out = tmp_path_factory.mktemp("fstseg")
    with_idx = SegmentBuilder(schema, SegmentGeneratorConfig(
        fst_index_columns=["msg"])).build(cols, str(out), "logs_fst")
    without_idx = SegmentBuilder(schema, SegmentGeneratorConfig()).build(
        cols, str(out), "logs_plain")
    return load_segment(with_idx), load_segment(without_idx)


def test_query_results_identical_with_and_without_index(fst_segment):
    seg_i, seg_p = fst_segment
    assert seg_i.column("msg").fst_index is not None
    assert seg_p.column("msg").fst_index is None
    for pattern in ["timeout", "host1[0-9]", "auth.*host3", "resets?",
                    "full|empty", "^ok", "no_such_message"]:
        sql = (f"SELECT COUNT(*), SUM(n) FROM logs "
               f"WHERE REGEXP_LIKE(msg, '{pattern}')")
        a = execute_query([seg_i], sql).rows
        b = execute_query([seg_p], sql).rows
        assert a == b, (pattern, a, b)
    # sanity: some patterns actually match
    n = execute_query([seg_i], "SELECT COUNT(*) FROM logs "
                               "WHERE REGEXP_LIKE(msg, 'timeout')").rows[0][0]
    assert n > 0


def test_reload_adds_fst_index(tmp_path):
    from pinot_tpu.segment.preprocess import preprocess_segment
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.table import IndexingConfig
    schema = Schema("logs", [dimension("msg", DataType.STRING),
                             metric("n", DataType.INT)])
    seg_dir = SegmentBuilder(schema).build(
        {"msg": ["alpha one", "beta two", "alpha three"],
         "n": np.array([1, 2, 3], dtype=np.int32)}, str(tmp_path), "logs_0")
    changes = preprocess_segment(seg_dir, IndexingConfig(fst_index_columns=["msg"]))
    assert any("added fst" in c for c in changes)
    seg = load_segment(seg_dir)
    assert seg.column("msg").fst_index is not None
    n = execute_query([seg], "SELECT COUNT(*) FROM logs "
                             "WHERE REGEXP_LIKE(msg, 'alpha')").rows[0][0]
    assert n == 2


def test_fst_handles_nul_in_values(tmp_path):
    vals = ["a\x00bcq", "yellow", "zebra", "zenith", "zzzzzz"]
    path = str(tmp_path / "nul.fst.npz")
    create_fst_index(path, vals)
    idx = FstIndexReader(path)
    got = ids_matching_regex_indexed(idx, vals, "zebra")
    assert got is not None and got.tolist() == [2]
    got = ids_matching_regex_indexed(idx, vals, "zzzz")
    assert got is not None and got.tolist() == [4]
    got = ids_matching_regex_indexed(idx, vals, "a\x00bc")
    assert got is not None and got.tolist() == [0]


def test_fst_skipped_for_bytes_columns(tmp_path):
    from pinot_tpu.schema import DataType, Schema, dimension, metric, FieldSpec, FieldRole
    schema = Schema("b", [FieldSpec("raw", DataType.BYTES, FieldRole.DIMENSION),
                          metric("n", DataType.INT)])
    seg = load_segment(SegmentBuilder(schema, SegmentGeneratorConfig(
        fst_index_columns=["raw"])).build(
        {"raw": [b"\x01\x02", b"\x03"], "n": np.array([1, 2], dtype=np.int32)},
        str(tmp_path), "b_0"))
    assert seg.column("raw").fst_index is None


def test_percentile_digit_suffix_mv_forms():
    from pinot_tpu.query.aggregates import make_agg
    from pinot_tpu.sql.ast import Function, Identifier
    for name, pct in [("percentile95mv", 95.0), ("percentileest90mv", 90.0),
                      ("percentiletdigest50mv", 50.0), ("percentilemv", None)]:
        args = (Identifier("scores"),) if pct is not None \
            else (Identifier("scores"), __import__("pinot_tpu.sql.ast", fromlist=["Literal"]).Literal(75))
        agg = make_agg(Function(name, args))
        assert agg.pct == (pct if pct is not None else 75.0), name
