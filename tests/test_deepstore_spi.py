"""Deep-store FS SPI: a full cluster lifecycle against a NON-local filesystem.

Reference: PinotFS SPI (pinot-spi/.../filesystem/PinotFS.java) with S3/GCS/ADLS
plugin implementations + PinotFSFactory. MemDeepStore has the same
bytes-by-URI shape as the remote plugins (no rename, no local paths), so every
deep-store interaction the roles make — upload, server download, deleted
parking, reaping — is proven to work through the SPI alone.
"""

import time

import numpy as np
import pytest

from pinot_tpu.cluster.broker import Broker
from pinot_tpu.cluster.catalog import Catalog
from pinot_tpu.cluster.controller import Controller
from pinot_tpu.cluster.deepstore import (DeepStoreFS, MemDeepStore, create_fs,
                                         register_fs)
from pinot_tpu.cluster.server import ServerNode
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.table import TableConfig


def test_create_fs_factory(tmp_path):
    fs = create_fs(f"local://{tmp_path}")
    fs.put_bytes(b"x", "a/b")
    assert fs.get_bytes("a/b") == b"x"
    mem = create_fs("mem://")
    assert isinstance(mem, MemDeepStore)
    with pytest.raises(ValueError):
        create_fs("gs://bucket")  # not registered in this build
    with pytest.raises(ValueError):
        create_fs("s3://bucket")  # s3 IS registered but needs ?endpoint=


def test_register_custom_fs():
    class MyFS(MemDeepStore):
        scheme = "myfs"
    register_fs("myfs", MyFS)
    assert isinstance(create_fs("myfs://root"), MyFS)


def test_cluster_lifecycle_on_mem_fs(tmp_path):
    """Upload -> assignment -> server download -> query -> delete/park -> reap,
    all through the in-memory FS (no local deep-store paths anywhere)."""
    catalog = Catalog()
    fs = MemDeepStore()
    ctrl = Controller("c0", catalog, fs, str(tmp_path / "ctrl"))
    server = ServerNode("server_0", catalog, fs, str(tmp_path / "s0"),
                        completion=ctrl.llc)
    broker = Broker("b0", catalog)
    broker.register_server_handle("server_0", server.execute_partial,
                                  explain_handle=server.explain_partial)

    schema = Schema("t", [dimension("k"), metric("v", DataType.DOUBLE)])
    ctrl.add_schema(schema)
    ctrl.add_table(TableConfig("t"))
    from pinot_tpu.segment.writer import SegmentBuilder
    seg_dir = SegmentBuilder(schema).build(
        {"k": ["a", "b", "a"], "v": np.array([1.0, 2.0, 3.0])},
        str(tmp_path / "build"), "t_0")
    ctrl.upload_segment("t_OFFLINE", seg_dir)

    res = broker.handle_query("SELECT k, SUM(v) FROM t GROUP BY k ORDER BY k LIMIT 5")
    assert res.rows == [["a", 4.0], ["b", 2.0]]
    assert fs.exists("t_OFFLINE/t_0.tar.gz")

    # delete parks in the mem FS (base-class copy+delete move, no rename)
    ctrl.delete_segment("t_OFFLINE", "t_0")
    assert not fs.exists("t_OFFLINE/t_0.tar.gz")
    assert fs.exists("Deleted_Segments/t_OFFLINE/t_0.tar.gz")
    ctrl.run_retention(now_ms=int(time.time() * 1000) + 8 * 86_400_000)
    assert not fs.exists("Deleted_Segments/t_OFFLINE/t_0.tar.gz")
