"""Mesh scatter/combine tests on the virtual 8-device CPU mesh.

Reference pattern: single-JVM multi-server tests (`QueryServerEnclosure`,
SURVEY.md §4.3) — a full distributed combine without real hardware.
"""

import numpy as np
import pytest

from pinot_tpu.parallel import MeshQueryExecutor, aligned_dictionaries, default_mesh
from pinot_tpu.query.executor import ServerQueryExecutor
from pinot_tpu.segment import SegmentGeneratorConfig, load_segment
from pinot_tpu.segment.writer import build_aligned_segments

from conftest import make_ssb_columns


@pytest.fixture(scope="module")
def aligned_segments(tmp_path_factory, ssb_schema):
    rng = np.random.default_rng(11)
    cols = make_ssb_columns(rng, 8192)
    out = tmp_path_factory.mktemp("aligned")
    paths = build_aligned_segments(ssb_schema, cols, str(out), "lineorder", 8)
    return [load_segment(p) for p in paths]


@pytest.fixture(scope="module")
def mesh_exec():
    return MeshQueryExecutor(default_mesh(8))


QUERIES = [
    "SELECT SUM(lo_extendedprice * lo_discount) FROM lineorder "
    "WHERE lo_discount BETWEEN 1 AND 3 AND lo_quantity < 25 LIMIT 100",
    "SELECT lo_region, SUM(lo_revenue), COUNT(*) FROM lineorder GROUP BY lo_region LIMIT 100",
    "SELECT lo_region, lo_category, MIN(lo_revenue), MAX(lo_quantity) FROM lineorder "
    "WHERE lo_region IN ('ASIA', 'EUROPE') GROUP BY lo_region, lo_category LIMIT 100",
    "SELECT DISTINCTCOUNT(lo_brand) FROM lineorder WHERE lo_quantity > 10 LIMIT 5",
    "SELECT AVG(lo_extendedprice), COUNT(*) FROM lineorder WHERE lo_brand LIKE 'MFGR#1%' LIMIT 5",
]


@pytest.mark.parametrize("sql", QUERIES)
def test_mesh_matches_single_device(aligned_segments, mesh_exec, sql):
    """The psum combine must agree with the per-segment host-merge path."""
    sharded = mesh_exec.execute(aligned_segments, sql)
    single = ServerQueryExecutor().execute(aligned_segments, sql)
    assert sorted(map(repr, _norm(sharded.rows))) == sorted(map(repr, _norm(single.rows)))


def _norm(rows):
    # float32 accumulation order differs between the psum and host-merge paths;
    # compare to 5 significant digits
    out = []
    for r in rows:
        out.append(tuple(float(f"{v:.5g}") if isinstance(v, float) else v for v in r))
    return out


def test_alignment_detection(aligned_segments, ssb_segment_dir):
    assert aligned_dictionaries(aligned_segments, ["lo_region", "lo_brand", "lo_orderdate"])
    other = load_segment(ssb_segment_dir[0])
    # lo_region happens to align (same 5 values everywhere); lo_orderdate is data-dependent
    assert not aligned_dictionaries(aligned_segments + [other], ["lo_orderdate"])


def test_unaligned_falls_back(aligned_segments, ssb_segment_dir, mesh_exec, ssb_schema):
    """Mixing in an unaligned segment must still produce correct (host-merged) results."""
    other = load_segment(ssb_segment_dir[0])
    segs = aligned_segments + [other]
    # the lo_orderdate LUT predicate hits an unaligned dictionary -> host-merge fallback
    sql = ("SELECT lo_region, COUNT(*) FROM lineorder WHERE lo_orderdate <= 19941231 "
           "GROUP BY lo_region LIMIT 100")
    res = mesh_exec.execute(segs, sql)
    single = ServerQueryExecutor().execute(segs, sql)
    assert sorted(map(repr, res.rows)) == sorted(map(repr, single.rows))


def test_segment_padding_not_multiple_of_devices(tmp_path_factory, ssb_schema, mesh_exec):
    """5 segments over 8 devices: padding segments must not perturb results."""
    rng = np.random.default_rng(13)
    cols = make_ssb_columns(rng, 2500)
    out = tmp_path_factory.mktemp("odd")
    paths = build_aligned_segments(ssb_schema, cols, str(out), "odd", 5)
    segs = [load_segment(p) for p in paths]
    sql = "SELECT COUNT(*), SUM(lo_revenue) FROM lineorder WHERE lo_discount <= 4 LIMIT 5"
    sharded = mesh_exec.execute(segs, sql)
    single = ServerQueryExecutor().execute(segs, sql)
    got, want = sharded.rows[0], single.rows[0]
    assert got[0] == want[0]
    assert got[1] == pytest.approx(want[1], rel=1e-3)
