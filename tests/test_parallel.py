"""Mesh scatter/combine tests on the virtual 8-device CPU mesh.

Reference pattern: single-JVM multi-server tests (`QueryServerEnclosure`,
SURVEY.md §4.3) — a full distributed combine without real hardware.
"""

import numpy as np
import pytest

from pinot_tpu.parallel import MeshQueryExecutor, aligned_dictionaries, default_mesh
from pinot_tpu.query.executor import ServerQueryExecutor
from pinot_tpu.segment import SegmentGeneratorConfig, load_segment
from pinot_tpu.segment.writer import build_aligned_segments

from conftest import make_ssb_columns


@pytest.fixture(scope="module")
def aligned_segments(tmp_path_factory, ssb_schema):
    rng = np.random.default_rng(11)
    cols = make_ssb_columns(rng, 8192)
    out = tmp_path_factory.mktemp("aligned")
    paths = build_aligned_segments(ssb_schema, cols, str(out), "lineorder", 8)
    return [load_segment(p) for p in paths]


@pytest.fixture(scope="module")
def mesh_exec():
    return MeshQueryExecutor(default_mesh(8))


QUERIES = [
    "SELECT SUM(lo_extendedprice * lo_discount) FROM lineorder "
    "WHERE lo_discount BETWEEN 1 AND 3 AND lo_quantity < 25 LIMIT 100",
    "SELECT lo_region, SUM(lo_revenue), COUNT(*) FROM lineorder GROUP BY lo_region LIMIT 100",
    "SELECT lo_region, lo_category, MIN(lo_revenue), MAX(lo_quantity) FROM lineorder "
    "WHERE lo_region IN ('ASIA', 'EUROPE') GROUP BY lo_region, lo_category LIMIT 100",
    "SELECT DISTINCTCOUNT(lo_brand) FROM lineorder WHERE lo_quantity > 10 LIMIT 5",
    "SELECT AVG(lo_extendedprice), COUNT(*) FROM lineorder WHERE lo_brand LIKE 'MFGR#1%' LIMIT 5",
]


@pytest.mark.parametrize("sql", QUERIES)
def test_mesh_matches_single_device(aligned_segments, mesh_exec, sql):
    """The psum combine must agree with the per-segment host-merge path."""
    sharded = mesh_exec.execute(aligned_segments, sql)
    single = ServerQueryExecutor().execute(aligned_segments, sql)
    assert sorted(map(repr, _norm(sharded.rows))) == sorted(map(repr, _norm(single.rows)))


def _norm(rows):
    # float32 accumulation order differs between the psum and host-merge paths;
    # compare to 5 significant digits
    out = []
    for r in rows:
        out.append(tuple(float(f"{v:.5g}") if isinstance(v, float) else v for v in r))
    return out


def test_alignment_detection(aligned_segments, ssb_segment_dir):
    assert aligned_dictionaries(aligned_segments, ["lo_region", "lo_brand", "lo_orderdate"])
    other = load_segment(ssb_segment_dir[0])
    # lo_region happens to align (same 5 values everywhere); lo_orderdate is data-dependent
    assert not aligned_dictionaries(aligned_segments + [other], ["lo_orderdate"])


def test_unaligned_falls_back(aligned_segments, ssb_segment_dir, mesh_exec, ssb_schema):
    """Mixing in an unaligned segment must still produce correct (host-merged) results."""
    other = load_segment(ssb_segment_dir[0])
    segs = aligned_segments + [other]
    # the lo_orderdate LUT predicate hits an unaligned dictionary -> host-merge fallback
    sql = ("SELECT lo_region, COUNT(*) FROM lineorder WHERE lo_orderdate <= 19941231 "
           "GROUP BY lo_region LIMIT 100")
    res = mesh_exec.execute(segs, sql)
    single = ServerQueryExecutor().execute(segs, sql)
    assert sorted(map(repr, res.rows)) == sorted(map(repr, single.rows))


def test_segment_padding_not_multiple_of_devices(tmp_path_factory, ssb_schema, mesh_exec):
    """5 segments over 8 devices: padding segments must not perturb results."""
    rng = np.random.default_rng(13)
    cols = make_ssb_columns(rng, 2500)
    out = tmp_path_factory.mktemp("odd")
    paths = build_aligned_segments(ssb_schema, cols, str(out), "odd", 5)
    segs = [load_segment(p) for p in paths]
    sql = "SELECT COUNT(*), SUM(lo_revenue) FROM lineorder WHERE lo_discount <= 4 LIMIT 5"
    sharded = mesh_exec.execute(segs, sql)
    single = ServerQueryExecutor().execute(segs, sql)
    got, want = sharded.rows[0], single.rows[0]
    assert got[0] == want[0]
    assert got[1] == pytest.approx(want[1], rel=1e-3)


# ---------------------------------------------------------------------------
# Merged-dictionary device path (unaligned segment sets, parallel/merged.py)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def unaligned_segments(tmp_path_factory, ssb_schema):
    """Segments built independently (per-chunk dictionaries): the realistic case of
    segments committed at different times without a shared ingestion dictionary."""
    from pinot_tpu.segment import SegmentBuilder, SegmentGeneratorConfig
    rng = np.random.default_rng(23)
    out = tmp_path_factory.mktemp("unaligned")
    segs = []
    from conftest import BRANDS
    for i in range(4):
        # different row counts and value mixes per segment -> misaligned dictionaries
        n = 1500 + 700 * i
        cols = make_ssb_columns(rng, n)
        sub = BRANDS[:10 + 8 * i]  # per-segment brand subset
        cols["lo_brand"] = [sub[j] for j in rng.integers(0, len(sub), n)]
        builder = SegmentBuilder(ssb_schema, SegmentGeneratorConfig())
        segs.append(load_segment(builder.build(cols, str(out), f"unaligned_{i}")))
    return segs


def test_unaligned_set_uses_device_plan(unaligned_segments, mesh_exec):
    from pinot_tpu.query.context import compile_query
    ctx = compile_query("SELECT lo_brand, COUNT(*) FROM lineorder GROUP BY lo_brand LIMIT 100",
                        unaligned_segments[0].schema)
    assert not aligned_dictionaries(unaligned_segments, ["lo_brand"])
    plan, view = mesh_exec._plan_for_set(ctx, unaligned_segments)
    assert plan.kind == "device" and view is not None
    # planning surface exposes the GLOBAL dictionary
    glob_card = plan.segment.column("lo_brand").cardinality
    assert glob_card >= max(s.column("lo_brand").cardinality for s in unaligned_segments)


@pytest.mark.parametrize("sql", QUERIES)
def test_merged_path_matches_host(unaligned_segments, mesh_exec, sql):
    """Remapped global ids must reproduce the host value-merge results exactly."""
    sharded = mesh_exec.execute(unaligned_segments, sql)
    single = ServerQueryExecutor().execute(unaligned_segments, sql)
    assert sorted(map(repr, _norm(sharded.rows))) == sorted(map(repr, _norm(single.rows)))


def test_merged_distinctcount_exact(unaligned_segments, mesh_exec):
    """Exact DISTINCTCOUNT across unaligned dictionaries: presence vectors must land in
    the global id space (per-segment ids would collide and undercount)."""
    sql = "SELECT DISTINCTCOUNT(lo_orderdate) FROM lineorder LIMIT 5"
    got = mesh_exec.execute(unaligned_segments, sql).rows[0][0]
    want = len({int(d) for s in unaligned_segments
                for d in s.column("lo_orderdate").values()})
    assert got == want


def test_mutable_segment_scans_on_device(unaligned_segments, mesh_exec, ssb_schema):
    """Consuming (mutable) segments ride the merged device path next to committed ones."""
    from pinot_tpu.segment.mutable import MutableSegment
    from pinot_tpu.query.context import compile_query
    rng = np.random.default_rng(31)
    cols = make_ssb_columns(rng, 257)
    mut = MutableSegment("consuming_0", ssb_schema)
    for r in range(257):
        mut.index({k: (v[r] if not isinstance(v, list) else v[r]) for k, v in cols.items()})
    segs = unaligned_segments + [mut]
    sql = ("SELECT lo_region, COUNT(*), SUM(lo_revenue) FROM lineorder "
           "WHERE lo_quantity < 40 GROUP BY lo_region LIMIT 100")
    ctx = compile_query(sql, ssb_schema)
    plan, view = mesh_exec._plan_for_set(ctx, segs)
    assert plan.kind == "device" and view is not None
    sharded = mesh_exec.execute(segs, sql)
    single = ServerQueryExecutor().execute(segs, sql)
    assert sorted(map(repr, _norm(sharded.rows))) == sorted(map(repr, _norm(single.rows)))


def test_mutable_growth_invalidates_view(unaligned_segments, mesh_exec, ssb_schema):
    """New rows in a consuming segment must appear in the next device-path answer."""
    from pinot_tpu.segment.mutable import MutableSegment
    rng = np.random.default_rng(37)
    cols = make_ssb_columns(rng, 64)
    mut = MutableSegment("consuming_1", ssb_schema)
    for r in range(32):
        mut.index({k: v[r] for k, v in cols.items()})
    segs = unaligned_segments + [mut]
    sql = "SELECT COUNT(*) FROM lineorder LIMIT 5"
    before = mesh_exec.execute(segs, sql).rows[0][0]
    for r in range(32, 64):
        mut.index({k: v[r] for k, v in cols.items()})
    after = mesh_exec.execute(segs, sql).rows[0][0]
    assert after == before + 32


def test_groupby_orderby_trim(aligned_segments, mesh_exec):
    """Mesh group-by with ORDER BY <agg> LIMIT k trims decode to k groups, exactly."""
    sql = ("SELECT lo_brand, SUM(lo_revenue) FROM lineorder "
           "GROUP BY lo_brand ORDER BY SUM(lo_revenue) DESC LIMIT 5")
    sharded = mesh_exec.execute(aligned_segments, sql)
    single = ServerQueryExecutor().execute(aligned_segments, sql)
    assert len(sharded.rows) == 5
    assert sorted(map(repr, _norm(sharded.rows))) == sorted(map(repr, _norm(single.rows)))
    # ascending + AVG variants
    for sql in [
        "SELECT lo_brand, COUNT(*) FROM lineorder GROUP BY lo_brand "
        "ORDER BY COUNT(*) LIMIT 7",
        "SELECT lo_brand, AVG(lo_extendedprice) FROM lineorder GROUP BY lo_brand "
        "ORDER BY AVG(lo_extendedprice) DESC LIMIT 3",
        "SELECT lo_brand, MIN(lo_revenue) FROM lineorder GROUP BY lo_brand "
        "ORDER BY MIN(lo_revenue) LIMIT 4 OFFSET 2",
    ]:
        sharded = mesh_exec.execute(aligned_segments, sql)
        single = ServerQueryExecutor().execute(aligned_segments, sql)
        assert sorted(map(repr, _norm(sharded.rows))) == sorted(map(repr, _norm(single.rows)))


def test_groupby_having_not_trimmed(aligned_segments, mesh_exec):
    """HAVING must see ALL groups (trim would drop groups HAVING could keep)."""
    sql = ("SELECT lo_brand, COUNT(*) FROM lineorder GROUP BY lo_brand "
           "HAVING COUNT(*) > 10 ORDER BY COUNT(*) DESC LIMIT 3")
    sharded = mesh_exec.execute(aligned_segments, sql)
    single = ServerQueryExecutor().execute(aligned_segments, sql)
    assert sorted(map(repr, _norm(sharded.rows))) == sorted(map(repr, _norm(single.rows)))


# -- doc-set filters + MV on the mesh kernel ---------------------------------

@pytest.fixture(scope="module")
def text_mv_segments(tmp_path_factory):
    """Aligned segments with a text-indexed column and an MV column."""
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.segment.writer import SegmentGeneratorConfig
    schema = Schema("docs", [
        dimension("body", DataType.STRING),
        dimension("tags", DataType.STRING, single_value=False),
        dimension("kind", DataType.STRING),
        metric("v", DataType.DOUBLE),
    ])
    rng = np.random.default_rng(29)
    n = 4000
    words = ["alpha", "beta", "gamma", "delta", "epsilon"]
    cols = {
        "body": [" ".join(rng.choice(words, 3)) for _ in range(n)],
        "tags": [list(rng.choice(["red", "green", "blue", "gold"],
                                 rng.integers(1, 4), replace=False))
                 for _ in range(n)],
        "kind": rng.choice(["a", "b", "c"], n).tolist(),
        "v": np.round(rng.uniform(0, 10, n), 3),
    }
    out = tmp_path_factory.mktemp("textmv")
    paths = build_aligned_segments(
        schema, cols, str(out), "docs", 8,
        config=SegmentGeneratorConfig(text_index_columns=["body"]))
    return [load_segment(p) for p in paths], cols


def test_text_match_agg_rides_mesh_kernel(text_mv_segments, mesh_exec):
    """TEXT_MATCH + aggregation: the doc-set bitmaps stack [S, rows] into the
    mesh kernel's docsets input instead of forcing per-segment fallback."""
    segs, cols = text_mv_segments
    ctx_plan, view = mesh_exec._plan_for_set(
        __import__("pinot_tpu.query.context", fromlist=["compile_query"])
        .compile_query("SELECT COUNT(*), SUM(v) FROM docs "
                       "WHERE TEXT_MATCH(body, 'alpha') AND kind = 'a'",
                       segs[0].schema), segs)
    assert ctx_plan is not None and ctx_plan.kind == "device"
    res = mesh_exec.execute(
        segs, "SELECT COUNT(*), SUM(v) FROM docs "
              "WHERE TEXT_MATCH(body, 'alpha') AND kind = 'a'")
    import numpy as _np
    want_mask = _np.array([("alpha" in b) and k == "a"
                           for b, k in zip(cols["body"], cols["kind"])])
    assert res.rows[0][0] == int(want_mask.sum())
    assert res.rows[0][1] == pytest.approx(
        float(_np.sum(_np.asarray(cols["v"])[want_mask])), rel=1e-5)


def test_mv_filter_group_by_rides_mesh_kernel(text_mv_segments, mesh_exec):
    """MV LUT filter ([S, rows, W] stacked ids) + SV group-by on the mesh
    kernel: any-value-matches semantics, grouped totals exact."""
    segs, cols = text_mv_segments
    from pinot_tpu.query.context import compile_query
    ctx = compile_query(
        "SELECT kind, COUNT(*) FROM docs WHERE tags = 'gold' "
        "GROUP BY kind ORDER BY kind LIMIT 10", segs[0].schema)
    plan, view = mesh_exec._plan_for_set(ctx, segs)
    assert plan is not None and plan.kind == "device", \
        getattr(plan, "fallback_reason", None)
    res = mesh_exec.execute(
        segs, "SELECT kind, COUNT(*) FROM docs WHERE tags = 'gold' "
              "GROUP BY kind ORDER BY kind LIMIT 10")
    want = {}
    for k, tags in zip(cols["kind"], cols["tags"]):
        if "gold" in tags:
            want[k] = want.get(k, 0) + 1
    assert {r[0]: r[1] for r in res.rows} == want


def test_mv_in_filter_matches_host(text_mv_segments, mesh_exec):
    segs, cols = text_mv_segments
    from pinot_tpu.query.executor import ServerQueryExecutor
    sql = ("SELECT COUNT(*), SUM(v) FROM docs "
           "WHERE tags IN ('red', 'blue') LIMIT 5")
    a = mesh_exec.execute(segs, sql)
    b = ServerQueryExecutor(use_device=False).execute(segs, sql)
    assert a.rows[0][0] == b.rows[0][0]
    assert a.rows[0][1] == pytest.approx(b.rows[0][1], rel=1e-5)


def test_docset_cache_distinguishes_predicates(text_mv_segments, mesh_exec):
    """Two TEXT_MATCH queries with different terms must never share a cached
    mask (the cache keys on the full predicate token)."""
    segs, cols = text_mv_segments
    a = mesh_exec.execute(segs, "SELECT COUNT(*) FROM docs "
                                "WHERE TEXT_MATCH(body, 'alpha')")
    b = mesh_exec.execute(segs, "SELECT COUNT(*) FROM docs "
                                "WHERE TEXT_MATCH(body, 'beta')")
    import numpy as _np
    want_a = sum("alpha" in x for x in cols["body"])
    want_b = sum("beta" in x for x in cols["body"])
    assert (a.rows[0][0], b.rows[0][0]) == (want_a, want_b)
    # repeat query hits the cache and stays correct
    a2 = mesh_exec.execute(segs, "SELECT COUNT(*) FROM docs "
                                 "WHERE TEXT_MATCH(body, 'alpha')")
    assert a2.rows[0][0] == want_a


def test_mesh_grouped_distinct_family(aligned_segments, mesh_exec):
    """r4: GROUP BY + DISTINCTCOUNT/HLL/THETA through the mesh (per-group
    presence matrices psum across devices) agrees with the single-device
    engine exactly."""
    sql = ("SELECT lo_region, DISTINCTCOUNT(lo_brand), "
           "DISTINCTCOUNTHLL(lo_orderdate), "
           "DISTINCTCOUNTTHETASKETCH(lo_custkey), COUNT(*) FROM lineorder "
           "WHERE lo_quantity < 40 GROUP BY lo_region ORDER BY lo_region "
           "LIMIT 100")
    sharded = mesh_exec.execute(aligned_segments, sql)
    single = ServerQueryExecutor().execute(aligned_segments, sql)
    assert sharded.rows == single.rows
