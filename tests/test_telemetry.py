"""End-to-end query telemetry: ExecutionStats threading, device kernel timing,
EXPLAIN ANALYZE, the slow-query log, and the /debug endpoint.

Reference coverage pattern: BrokerResponseNative metadata assertions in the
reference's integration tests, plus its slow-query WARN log — here the record
is typed (`pinot_tpu.query.stats.ExecutionStats`) and must survive BOTH the
in-proc and the HTTP transport unchanged.
"""

import json
import logging
import re
import threading

import numpy as np
import pytest

from pinot_tpu.cluster import QuickCluster
from pinot_tpu.query import stats as qstats
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.table import TableConfig
from pinot_tpu.utils.metrics import Histogram, MetricsRegistry, get_registry
from pinot_tpu.utils.trace import Trace, current_depth, span

# the keys the acceptance criteria name: every query response must carry them
ACCEPTANCE_KEYS = (
    "numSegmentsQueried", "numSegmentsPruned", "numSegmentsMatched",
    "numDocsScanned", "deviceLaunches", "compileCacheHits",
    "compileCacheMisses", "deviceExecMs", "phaseTimesMs", "timeUsedMs",
)


@pytest.fixture
def tel_cluster(tmp_path):
    schema = Schema("ev", [dimension("site", DataType.STRING),
                           metric("v", DataType.LONG)])
    cluster = QuickCluster(num_servers=2, work_dir=str(tmp_path))
    cfg = TableConfig("ev", replication=1)
    cluster.create_table(schema, cfg)
    rng = np.random.default_rng(0)
    for _ in range(3):
        cluster.ingest_columns(cfg, {
            "site": np.array(["a", "b", "c", "d"] * 25),
            "v": rng.integers(0, 100, 100),
        })
    return cluster


class _CaptureHandler(logging.Handler):
    def __init__(self):
        super().__init__()
        self.records = []

    def emit(self, record):
        self.records.append(record)


@pytest.fixture
def slow_log_capture():
    logger = logging.getLogger("pinot_tpu.broker.slow_query")
    h = _CaptureHandler()
    logger.addHandler(h)
    try:
        yield h
    finally:
        logger.removeHandler(h)


# -- tentpole: stats through the in-proc broker ------------------------------

def test_groupby_stats_through_inproc_broker(tel_cluster):
    res = tel_cluster.query(
        "SELECT site, SUM(v) FROM ev GROUP BY site ORDER BY site")
    for key in ACCEPTANCE_KEYS:
        assert key in res.stats, f"missing {key}: {sorted(res.stats)}"
    assert res.stats["numSegmentsQueried"] == 3
    assert res.stats["numSegmentsPruned"] == 0
    assert res.stats["numSegmentsMatched"] == 3
    assert res.stats["numDocsScanned"] == 300
    # broker phase wall times keep their exact shape
    assert set(res.stats["phaseTimesMs"]) == {"compile", "scatter", "reduce"}
    # the op:* EXPLAIN ANALYZE breakdown never leaks into the public response
    assert not any(k.startswith("op:") for k in res.stats)


def test_segment_pruning_counted(tel_cluster):
    res = tel_cluster.query("SELECT COUNT(*) FROM ev WHERE site = 'nope'")
    # the constant-false fold happens per segment: all pruned, none matched
    assert res.stats["numSegmentsPruned"] + res.stats["numSegmentsQueried"] == 3
    assert res.stats["numSegmentsMatched"] <= res.stats["numSegmentsQueried"]


def test_compile_cache_hits_on_repeat_query(tel_cluster):
    sql = "SELECT site, SUM(v), MAX(v) FROM ev GROUP BY site"
    tel_cluster.query(sql)      # warm: builds whatever executables are needed
    res = tel_cluster.query(sql)
    assert res.stats["compileCacheMisses"] == 0, res.stats
    if res.stats["deviceLaunches"]:     # device path: cache must have served it
        assert res.stats["compileCacheHits"] >= 1


# -- tentpole: EXPLAIN ANALYZE -----------------------------------------------

def test_explain_analyze_renders_rows_and_ms(tel_cluster):
    res = tel_cluster.query(
        "EXPLAIN ANALYZE SELECT site, SUM(v) FROM ev GROUP BY site")
    assert res.columns == ["Operator", "Operator_Id", "Parent_Id", "Rows", "Ms"]
    assert res.stats.get("analyze") is True and res.stats.get("explain") is True
    # root row: result row count + total wall time
    root = res.rows[0]
    assert root[1] == 0 and root[2] == -1
    assert root[3] == 4 and root[4] > 0
    # per-node annotation: at least combine + segment plan carry rows/ms
    annotated = {r[0].split("(")[0] for r in res.rows if r[4] is not None}
    assert "COMBINE_GROUP_BY" in annotated
    assert "SEGMENT_PLAN" in annotated
    seg_rows = [r[3] for r in res.rows
                if r[0].startswith("SEGMENT_PLAN") and r[3] is not None]
    assert seg_rows and seg_rows[0] == 300      # docs actually scanned
    # the full stats record rides along
    assert res.stats["numSegmentsQueried"] == 3


def test_plain_explain_stays_three_columns(tel_cluster):
    res = tel_cluster.query(
        "EXPLAIN PLAN FOR SELECT site, SUM(v) FROM ev GROUP BY site")
    assert res.columns == ["Operator", "Operator_Id", "Parent_Id"]
    assert all(len(r) == 3 for r in res.rows)


def test_explain_analyze_single_node_executor(tmp_path):
    from pinot_tpu.query.executor import execute_query
    from pinot_tpu.segment.writer import SegmentBuilder, SegmentGeneratorConfig
    schema = Schema("t", [dimension("k", DataType.STRING),
                          metric("x", DataType.LONG)])
    seg = SegmentBuilder(schema, SegmentGeneratorConfig()).build(
        {"k": np.array(["p", "q", "p"], dtype=object),
         "x": np.array([1, 2, 3], dtype=np.int64)}, str(tmp_path), "t_0")
    from pinot_tpu.segment.reader import load_segment
    res = execute_query([load_segment(seg)],
                        "EXPLAIN ANALYZE SELECT k, SUM(x) FROM t GROUP BY k")
    assert res.columns == ["Operator", "Operator_Id", "Parent_Id", "Rows", "Ms"]
    assert res.rows[0][3] == 2 and res.rows[0][4] > 0
    assert res.stats["numSegmentsQueried"] == 1


# -- tentpole: slow-query log + /debug ---------------------------------------

def test_slow_query_emits_exactly_one_log_line(tel_cluster, slow_log_capture):
    cat = tel_cluster.broker.catalog
    counter = get_registry().counter("pinot_broker_slow_queries")
    before = counter.value
    cat.put_property("clusterConfig/broker.slow.query.ms", "0")
    try:
        tel_cluster.query("SELECT COUNT(*) FROM ev")
    finally:
        cat.put_property("clusterConfig/broker.slow.query.ms", None)
    assert len(slow_log_capture.records) == 1
    entry = json.loads(slow_log_capture.records[0].getMessage())
    assert entry["sql"] == "SELECT COUNT(*) FROM ev"
    assert entry["timeUsedMs"] > 0
    assert entry["thresholdMs"] == 0.0
    assert entry["stats"]["numServersResponded"] >= 1
    assert counter.value == before + 1
    # below threshold: silent
    tel_cluster.query("SELECT COUNT(*) FROM ev")
    assert len(slow_log_capture.records) == 1


def test_slow_query_log_carries_trace_spans(tel_cluster, slow_log_capture):
    cat = tel_cluster.broker.catalog
    cat.put_property("clusterConfig/broker.slow.query.ms", "0")
    try:
        tel_cluster.query("SELECT COUNT(*) FROM ev OPTION(trace=true)")
    finally:
        cat.put_property("clusterConfig/broker.slow.query.ms", None)
    entry = json.loads(slow_log_capture.records[-1].getMessage())
    assert entry["traceSpans"], entry
    assert any(s["name"] == "compile" for s in entry["traceSpans"])


def test_debug_stats_rollup(tel_cluster, slow_log_capture):
    cat = tel_cluster.broker.catalog
    cat.put_property("clusterConfig/broker.slow.query.ms", "0")
    try:
        tel_cluster.query("SELECT COUNT(*) FROM ev")
    finally:
        cat.put_property("clusterConfig/broker.slow.query.ms", None)
    dbg = tel_cluster.broker.debug_stats()
    qs = dbg["queryStats"]
    assert qs["numQueries"] >= 1
    assert qs["numSlowQueries"] >= 1
    assert qs["maxTimeMs"] >= qs["avgTimeMs"] > 0
    assert dbg["recentSlowQueries"][-1]["sql"] == "SELECT COUNT(*) FROM ev"
    assert "pinot_broker_queries" in dbg["brokerMetrics"]


# -- satellite 3: device pipeline counters surface per query -----------------

def test_device_pipeline_counters_in_query_stats(tmp_path, tel_cluster):
    from pinot_tpu.cluster.device_server import DeviceQueryPipeline
    pipeline = DeviceQueryPipeline()
    for server in tel_cluster.servers:
        server.device_pipeline = pipeline
    try:
        res = tel_cluster.query("SELECT COUNT(*), SUM(v) FROM ev WHERE v >= 0")
        assert res.rows[0][0] == 300
        if res.stats["deviceLaunches"]:     # served through the pipeline
            assert "queueWaitMs" in res.stats
            assert "dedupedLaunches" in res.stats
            assert "stackedLaunches" in res.stats
            assert res.stats["queueWaitMs"] >= 0
    finally:
        for server in tel_cluster.servers:
            server.device_pipeline = None
        pipeline.stop()


# -- satellite 1: Histogram.observe is atomic under concurrency --------------

def test_histogram_observe_concurrent():
    h = Histogram(buckets=(1.0, 10.0, 100.0))
    n_threads, per_thread = 8, 4000
    values = [0.5, 5.0, 50.0, 500.0]
    stop = threading.Event()
    torn = []

    def reader():
        # percentile() reads count + bucket rows together; a torn observe
        # would let the cumulative walk run past count and fall off the end
        while not stop.is_set():
            p = h.percentile(0.99)
            if p < 0:
                torn.append(p)

    def writer(i):
        for j in range(per_thread):
            h.observe(values[(i + j) % len(values)])

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(n_threads)]
    r = threading.Thread(target=reader)
    r.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    r.join()
    assert not torn
    total = n_threads * per_thread
    assert h.count == total
    # the atomic observe keeps the cumulative-bucket invariant exact: every
    # observation landed in exactly one bucket row
    assert sum(h.bucket_counts) == total
    assert h.bucket_counts == [total // 4] * 4


# -- satellite 2: spliced trace spans nest under the dispatch span -----------

def test_splice_applies_depth_offset():
    tr = Trace("q1")
    tr.record("server:s1", 0.0, 9.0, depth=1)
    remote = [{"name": "query", "startMs": 0.0, "durationMs": 5.0, "depth": 0},
              {"name": "segment:a", "startMs": 1.0, "durationMs": 2.0,
               "depth": 1}]
    tr.splice(remote, prefix="server:s1", offset_ms=3.0, depth_offset=2)
    by_name = {s["name"]: s for s in tr.to_rows()}
    assert by_name["server:s1/query"]["depth"] == 2
    assert by_name["server:s1/segment:a"]["depth"] == 3
    assert by_name["server:s1/query"]["startMs"] == 3.0


def test_current_depth_tracks_open_spans():
    tr = Trace("q2")
    with tr.activate():
        assert current_depth() == 0
        with span("outer"):
            assert current_depth() == 1
            with span("inner"):
                assert current_depth() == 2
        assert current_depth() == 0


# -- satellite 4: Prometheus exposition with multiple label sets -------------

def test_prometheus_histogram_multiple_labelsets():
    reg = MetricsRegistry()
    reg.histogram("lat_ms", {"table": "trips"}, buckets=(1.0, 10.0)).observe(0.5)
    reg.histogram("lat_ms", {"table": 'we"ird\nname'},
                  buckets=(1.0, 10.0)).observe(5.0)
    text = reg.render_prometheus()
    # exactly ONE # TYPE line for the family, both series grouped under it
    assert text.count("# TYPE lat_ms histogram") == 1
    assert 'lat_ms_bucket{table="trips",le="1"} 1' in text
    # label escaping: literal quote -> \" and newline -> \n, series intact
    assert 'table="we\\"ird\\nname"' in text
    for line in text.splitlines():
        assert "\n" not in line        # escaping kept the exposition line-safe
    assert 'lat_ms_count{table="trips"} 1' in text


def test_snapshot_reports_histogram_p50():
    reg = MetricsRegistry()
    h = reg.histogram("scan_ms", buckets=(1.0, 10.0, 100.0))
    for v in (0.5, 5.0, 5.0, 50.0):
        h.observe(v)
    snap = reg.snapshot()
    assert snap["scan_ms_count"] == 4
    assert snap["scan_ms_sum"] == pytest.approx(60.5)
    # p50 reads back as the upper bound of the bucket holding the median
    assert snap["scan_ms_p50"] == 10.0


# -- HTTP transport: same stats over the wire --------------------------------

def test_stats_and_debug_over_http(tmp_path):
    from conftest import wait_until
    from pinot_tpu.cluster.broker import Broker
    from pinot_tpu.cluster.catalog import Catalog
    from pinot_tpu.cluster.controller import Controller
    from pinot_tpu.cluster.deepstore import LocalDeepStore
    from pinot_tpu.cluster.http_service import http_call
    from pinot_tpu.cluster.process import BrokerClient, ControllerClient
    from pinot_tpu.cluster.remote import ControllerDeepStore, RemoteCatalog
    from pinot_tpu.cluster.server import ServerNode
    from pinot_tpu.cluster.services import (BrokerService, ControllerService,
                                            ServerService)
    from pinot_tpu.segment.writer import SegmentBuilder, SegmentGeneratorConfig

    schema = Schema("ev", [dimension("site", DataType.STRING),
                           metric("v", DataType.LONG)])
    catalog = Catalog()
    controller = Controller("controller_0", catalog,
                            LocalDeepStore(str(tmp_path / "ds")),
                            str(tmp_path / "ctrl"))
    csvc = ControllerService(controller)
    services, catalogs = [csvc], []
    try:
        rc = RemoteCatalog(csvc.url, poll_timeout_s=1.0)
        catalogs.append(rc)
        node = ServerNode("server_0", rc, ControllerDeepStore(csvc.url),
                          str(tmp_path / "server_0"))
        services.append(ServerService(node))
        brc = RemoteCatalog(csvc.url, poll_timeout_s=1.0)
        catalogs.append(brc)
        broker = Broker("broker_http", brc)
        bsvc = BrokerService(broker)
        services.append(bsvc)

        cc = ControllerClient(csvc.url)
        cc.add_schema(schema)
        cfg = TableConfig("ev", replication=1)
        cc.add_table(cfg)
        seg = SegmentBuilder(schema, SegmentGeneratorConfig()).build(
            {"site": np.array(["a", "b", "a", "c"], dtype=object),
             "v": np.array([1, 2, 3, 4], dtype=np.int64)},
            str(tmp_path / "b"), "ev_0")
        cc.upload_segment(cfg.table_name_with_type, seg)
        assert wait_until(
            lambda: len(node.segments_served(cfg.table_name_with_type)) == 1,
            timeout=15.0, interval=0.05, swallow=())

        bc = BrokerClient(bsvc.url)

        def grouped():
            try:
                return bc.query("SELECT site, SUM(v) FROM ev GROUP BY site "
                                "ORDER BY site")
            except Exception:
                return None     # broker catalog mirror still converging

        assert wait_until(lambda: grouped() is not None, timeout=15.0,
                          interval=0.1, swallow=())
        resp = grouped()
        assert resp["resultTable"]["rows"] == [["a", 4], ["b", 2], ["c", 4]]
        # the full merged record survives the HTTP hop, spread at top level
        for key in ACCEPTANCE_KEYS:
            assert key in resp, f"missing {key}: {sorted(resp)}"
        assert resp["numSegmentsQueried"] == 1
        assert resp["numDocsScanned"] == 4
        assert set(resp["phaseTimesMs"]) == {"compile", "scatter", "reduce"}

        # EXPLAIN ANALYZE over HTTP: annotated 5-column plan
        an = bc.query("EXPLAIN ANALYZE SELECT site, SUM(v) FROM ev GROUP BY site")
        cols = an["resultTable"]["dataSchema"]["columnNames"]
        assert cols == ["Operator", "Operator_Id", "Parent_Id", "Rows", "Ms"]
        assert an["resultTable"]["rows"][0][3] == 3     # result groups
        assert an["analyze"] is True

        # satellite 2: remote server spans splice in NESTED under the
        # broker's server:<id> dispatch span (depth_offset=current_depth())
        traced = bc.query("SELECT COUNT(*) FROM ev OPTION(trace=true)")
        spans = traced["traceInfo"]
        remote = [s for s in spans
                  if re.match(r"server:server_\d+/", s["name"])]
        assert remote, [s["name"] for s in spans]
        dispatch_depth = {s["name"]: s["depth"] for s in spans
                          if re.fullmatch(r"server:server_\d+", s["name"])}
        assert dispatch_depth
        for s in remote:
            root = s["name"].split("/", 1)[0]
            assert s["depth"] > dispatch_depth[root], s

        # GET /debug: rollups + slow ring as JSON
        catalog.put_property("clusterConfig/broker.slow.query.ms", "0")
        try:
            bc.query("SELECT COUNT(*) FROM ev")
        finally:
            catalog.put_property("clusterConfig/broker.slow.query.ms", None)
        dbg = json.loads(http_call("GET", f"{bsvc.url}/debug").decode())
        assert dbg["queryStats"]["numQueries"] >= 2
        assert dbg["queryStats"]["numSlowQueries"] >= 1
        assert dbg["recentSlowQueries"][-1]["sql"] == "SELECT COUNT(*) FROM ev"
    finally:
        for c in catalogs:
            c.close()
        for s in services:
            s.stop()


# -- glossary drift guard + report tool --------------------------------------

def _readme_documented_keys():
    import os
    readme = os.path.join(os.path.dirname(__file__), "..", "README.md")
    with open(readme) as f:
        text = f.read()
    obs = text.split("## Observability", 1)[1].split("## Layout", 1)[0]
    return set(re.findall(r"`([A-Za-z][A-Za-z.]*)`", obs))


def test_every_stats_constant_documented_in_readme():
    documented = _readme_documented_keys()
    for key in qstats.COUNTER_KEYS + qstats.BROKER_KEYS:
        assert key in documented, f"{key} missing from README glossary"
    assert "broker.slow.query.ms" in documented


def test_emitted_stats_keys_documented(tel_cluster):
    """Drift guard: every key a real query emits is in the README glossary."""
    documented = _readme_documented_keys()
    res = tel_cluster.query("SELECT site, SUM(v) FROM ev GROUP BY site")
    undocumented = set(res.stats) - documented
    assert not undocumented, (
        f"stats keys {sorted(undocumented)} are emitted but not documented "
        "in README.md's Observability glossary — add them there AND to "
        "pinot_tpu/query/stats.py's key tables")


def test_every_registered_metric_documented_in_readme():
    """Drift guard, now delegated to graftcheck's drift-metric-glossary rule:
    the static form covers EVERY registry call site in the package — not just
    the ones a query in this test run happens to execute."""
    from pinot_tpu.analysis import run_project
    from pinot_tpu.analysis.drift_guards import MetricGlossaryRule
    findings, _suppressed, _ctx = run_project(rules=[MetricGlossaryRule()])
    assert not findings, "\n".join(f.render() for f in findings)


def test_query_report_renders_waterfall(tel_cluster, capsys):
    from pinot_tpu.tools.query_report import _extract_stats, render_report
    res = tel_cluster.query("SELECT site, SUM(v) FROM ev GROUP BY site")
    body = render_report(_extract_stats(dict(res.stats)))
    assert "phase waterfall" in body
    assert "compile" in body and "scatter" in body and "reduce" in body
    assert "numDocsScanned" in body and "300" in body
    # also accepts a full response body and a slow-log entry
    body2 = render_report(_extract_stats({"sql": "SELECT 1",
                                          "stats": dict(res.stats)}))
    assert body2.startswith("query: SELECT 1")
