"""SQL parser + QueryContext compilation tests (reference pattern:
CalciteSqlParser tests in pinot-common)."""

import pytest

from pinot_tpu.query import QueryValidationError, compile_query
from pinot_tpu.sql import SqlSyntaxError, parse_query
from pinot_tpu.sql.ast import Function, Identifier, Literal


def test_basic_select():
    q = parse_query("SELECT a, b FROM t")
    assert q.table == "t"
    assert q.select == [(Identifier("a"), None), (Identifier("b"), None)]
    assert q.limit == 10  # default broker limit


def test_aggregation_group_by():
    q = parse_query(
        "SELECT lo_region, SUM(lo_revenue) AS total FROM lineorder "
        "WHERE lo_discount BETWEEN 1 AND 3 AND lo_quantity < 25 "
        "GROUP BY lo_region HAVING SUM(lo_revenue) > 100 "
        "ORDER BY total DESC LIMIT 5")
    assert q.select[1] == (Function("sum", (Identifier("lo_revenue"),)), "total")
    assert q.where.name == "and"
    assert q.group_by == [Identifier("lo_region")]
    assert q.having == Function("gt", (Function("sum", (Identifier("lo_revenue"),)), Literal(100)))
    assert q.order_by[0].desc
    assert q.limit == 5


def test_operator_precedence():
    q = parse_query("SELECT a + b * c - d FROM t")
    e = q.select[0][0]
    # ((a + (b*c)) - d)
    assert e == Function("minus", (
        Function("plus", (Identifier("a"), Function("times", (Identifier("b"), Identifier("c"))))),
        Identifier("d")))


def test_where_precedence_and_or_not():
    q = parse_query("SELECT a FROM t WHERE x = 1 OR y = 2 AND NOT z = 3")
    e = q.where
    assert e.name == "or"
    assert e.args[1].name == "and"
    assert e.args[1].args[1].name == "not"


def test_in_between_like_null():
    q = parse_query("SELECT a FROM t WHERE c IN ('x', 'y') AND d NOT IN (1, 2) "
                    "AND e NOT BETWEEN 1 AND 2 AND f LIKE 'A%' AND g IS NOT NULL")
    kinds = []
    def collect(e):
        if isinstance(e, Function):
            if e.name == "and":
                for a in e.args:
                    collect(a)
            else:
                kinds.append(e.name)
    collect(q.where)
    assert kinds == ["in", "not_in", "not", "like", "is_not_null"]


def test_count_star_distinct_cast_case():
    q = parse_query("SELECT COUNT(*), COUNT(DISTINCT u), CAST(x AS LONG), "
                    "CASE WHEN a > 1 THEN 'hi' ELSE 'lo' END FROM t")
    count, cdist, cast, case = [e for e, _ in q.select]
    assert count == Function("count", (Identifier("*"),))
    assert cdist.distinct and cdist.name == "count"
    assert cast == Function("cast", (Identifier("x"), Literal("LONG")))
    assert case.name == "case" and len(case.args) == 3


def test_options_and_limit_offset():
    q = parse_query("SET useMultistageEngine = true; SELECT a FROM t LIMIT 7 OFFSET 3 "
                    "OPTION(timeoutMs=100)")
    assert q.options == {"useMultistageEngine": True, "timeoutMs": 100}
    assert (q.limit, q.offset) == (7, 3)
    q2 = parse_query("SELECT a FROM t LIMIT 3, 7")
    assert (q2.offset, q2.limit) == (3, 7)


def test_quoted_identifiers_and_strings():
    q = parse_query('SELECT "weird col" FROM t WHERE s = \'it''s\'')
    assert q.select[0][0] == Identifier("weird col")


def test_negative_numbers_and_unary():
    q = parse_query("SELECT -3, -x FROM t WHERE a > -1.5e2")
    assert q.select[0][0] == Literal(-3)
    assert q.select[1][0] == Function("minus", (Literal(0), Identifier("x")))
    assert q.where.args[1] == Literal(-150.0)


def test_syntax_errors():
    for bad in ["SELECT FROM t", "SELECT a t", "SELECT a FROM t WHERE", "FOO BAR",
                "SELECT a FROM t GROUP 1", "SELECT a FROM t trailing junk ("]:
        with pytest.raises(SqlSyntaxError):
            parse_query(bad)


# -- QueryContext compilation ------------------------------------------------

def test_context_ordinal_and_alias_resolution(ssb_schema):
    ctx = compile_query(
        "SELECT lo_region AS r, SUM(lo_revenue) AS total FROM lineorder "
        "GROUP BY 1 ORDER BY total DESC", ssb_schema)
    assert ctx.group_by == [Identifier("lo_region")]
    assert ctx.order_by[0].expr == Function("sum", (Identifier("lo_revenue"),))
    assert ctx.aggregations == [Function("sum", (Identifier("lo_revenue"),))]
    assert ctx.output_names == ["r", "total"]


def test_context_star_expansion(ssb_schema):
    ctx = compile_query("SELECT * FROM lineorder", ssb_schema)
    assert ctx.output_names == ssb_schema.column_names


def test_context_validations(ssb_schema):
    with pytest.raises(QueryValidationError, match="unknown column"):
        compile_query("SELECT nope FROM lineorder", ssb_schema)
    with pytest.raises(QueryValidationError, match="neither aggregated"):
        compile_query("SELECT lo_region, SUM(lo_revenue) FROM lineorder", ssb_schema)
    with pytest.raises(QueryValidationError, match="WHERE"):
        compile_query("SELECT lo_region FROM lineorder WHERE SUM(lo_revenue) > 1", ssb_schema)
    with pytest.raises(QueryValidationError, match="nested"):
        compile_query("SELECT SUM(MAX(lo_revenue)) FROM lineorder", ssb_schema)


def test_context_dedups_aggregations(ssb_schema):
    ctx = compile_query(
        "SELECT SUM(lo_revenue), SUM(lo_revenue) + COUNT(*) FROM lineorder", ssb_schema)
    names = [a.name for a in ctx.aggregations]
    assert names == ["sum", "count"]
