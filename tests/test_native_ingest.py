"""Native ingest fast paths: C value splicer + schema-directed columnar
JSON decode + the realtime pump's decode-strategy selection (VERDICT r4 #4).

The C paths must be byte-exact against the pure-Python pipeline: fuzzed
differentials pin splice_record_batches against decode_record_batches and
columns_from_spliced_json against TransformPipeline.apply.
"""

import json

import numpy as np
import pytest

from pinot_tpu.ingest import kafka_wire as kw
from pinot_tpu.ingest.transform import (TransformPipeline,
                                        columns_from_spliced_json,
                                        rows_to_all_columns)
from pinot_tpu.schema import DataType, Schema, date_time, dimension, metric


def _schema():
    return Schema("events", [
        dimension("site", DataType.STRING), metric("clicks", DataType.LONG),
        metric("cost", DataType.DOUBLE), date_time("ts", DataType.LONG)])


def _native_available() -> bool:
    from pinot_tpu.native import get_lib
    return get_lib() is not None


pytestmark = pytest.mark.skipif(not _native_available(),
                                reason="no C compiler for the native lib")


def test_splice_matches_decode():
    rng = np.random.default_rng(3)
    values = [json.dumps({"v": int(v), "s": f"x{v % 7}"}).encode()
              for v in rng.integers(0, 1000, 500)]
    batches = b""
    off = 0
    for lo in range(0, len(values), 37):   # several batches
        chunk = values[lo:lo + 37]
        batches += kw.encode_record_batch(
            off, [(None, v, 1700000000000 + i) for i, v in enumerate(chunk)])
        off += len(chunk)
    for min_off in (0, 100, 499, 500):
        out = kw.splice_record_batches(batches, min_off)
        assert out is not None
        data, n, last = out
        want = [v for o, _ts, _k, v in kw.decode_record_batches(batches)
                if o >= min_off]
        assert n == len(want)
        assert data == b",".join(want)
        if want:
            assert last == off - 1
    # max_records cap is EXACT (consume catch-up targets depend on it)
    data, n, last = kw.splice_record_batches(batches, 0, max_records=50)
    assert n == 50 and data == b",".join(values[:50]) and last == 49


def test_columns_fuzz_vs_pipeline():
    rng = np.random.default_rng(11)
    schema = _schema()
    pipeline = TransformPipeline(schema)
    for trial in range(20):
        rows = []
        for i in range(rng.integers(1, 120)):
            row = {}
            if rng.random() < 0.95:
                row["site"] = rng.choice(
                    ["plain", 'quo"te', "unié", "", "tab\there"])
            if rng.random() < 0.9:
                row["clicks"] = int(rng.integers(-2**40, 2**40))
            if rng.random() < 0.9:
                row["cost"] = [1.5, -0.25, 1e12, 3, None][rng.integers(0, 5)]
            if rng.random() < 0.8:
                row["ts"] = int(rng.integers(0, 2**45))
            if rng.random() < 0.3:
                row["extra"] = {"nested": [1, {"deep": "x"}]}
            rows.append(row)
        data = ",".join(json.dumps(r) for r in rows).encode()
        got = columns_from_spliced_json(data, len(rows), schema)
        assert got is not None
        want = pipeline.apply(rows_to_all_columns(rows))
        assert set(got) == set(want)
        for k in want:
            assert len(got[k]) == len(want[k])
            for a, b in zip(got[k], want[k]):
                if isinstance(b, float):
                    assert a == pytest.approx(b, rel=1e-12), (trial, k)
                else:
                    assert a == b and type(a) is type(b), (trial, k, a, b)


def test_columns_int64_overflow_and_missing():
    schema = _schema()
    rows = [{"site": "a", "clicks": 2**70, "cost": 1.0, "ts": 1},
            {"site": "b"}]
    data = ",".join(json.dumps(r) for r in rows).encode()
    got = columns_from_spliced_json(data, 2, schema)
    want = TransformPipeline(schema).apply(rows_to_all_columns(rows))
    assert got == want
    assert got["clicks"][0] == 2**70          # bad-row python re-parse
    assert got["clicks"][1] is None


def test_columns_declines_mv_schema():
    schema = Schema("t", [dimension("tags", DataType.STRING,
                                    single_value=False)])
    assert columns_from_spliced_json(b'{"tags":["a"]}', 1, schema) is None


def test_pump_takes_columnar_path(tmp_path):
    """The realtime pump over a kafkalite stream must select a native
    columnar path (never per-row decode) for a plain JSON table, and the
    indexed rows must match what was produced."""
    from pinot_tpu.cluster import QuickCluster
    from pinot_tpu.ingest.kafkalite import LogBrokerClient, LogBrokerServer
    from pinot_tpu.table import StreamConfig, TableConfig, TableType

    schema = _schema()
    srv = LogBrokerServer()
    try:
        client = LogBrokerClient(srv.bootstrap)
        client.create_topic("ev_native", 1)
        payloads = [json.dumps({"site": f"s{i % 5}", "clicks": i,
                                "cost": i * 0.5, "ts": i}) for i in range(500)]
        client.produce_many("ev_native", payloads)
        cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
        cfg = TableConfig("events", table_type=TableType.REALTIME,
                          stream=StreamConfig(
                              stream_type="kafkalite", topic="ev_native",
                              properties={"bootstrap": srv.bootstrap},
                              flush_threshold_rows=10_000))
        cluster.create_realtime_table(schema, cfg, num_partitions=1)
        table = cfg.table_name_with_type
        cluster.pump_realtime(table)
        mgr = cluster.servers[0].realtime_manager(table)
        consumers = list(mgr.consumers.values())
        assert consumers, "no consuming segment"
        # "columnar-array" is the vectorized array-native decode (preferred);
        # "columnar" is the list-based native decode it supersedes
        assert consumers[0].last_decode_path in ("columnar-array", "columnar"), \
            consumers[0].last_decode_path
        res = cluster.query("SELECT COUNT(*), SUM(clicks) FROM events")
        assert res.rows[0][0] == 500
        assert res.rows[0][1] == sum(range(500))
    finally:
        srv.stop()
