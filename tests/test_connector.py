"""External read connector: splits per server, filter/projection pushdown,
parallel Arrow fetch straight from the servers (Spark-read-connector analog;
reference: PinotSplitter.scala / FilterPushDown.scala /
PinotServerDataFetcher.scala).
"""

import numpy as np
import pytest

from pinot_tpu.cluster.process import ProcessCluster
from pinot_tpu.connector import PinotReader, read_table
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.segment.writer import SegmentBuilder
from pinot_tpu.table import TableConfig

from conftest import wait_until


@pytest.fixture(scope="module")
def cluster_with_trips(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("connector")
    schema = Schema("trips", [
        dimension("city", DataType.STRING),
        metric("fare", DataType.DOUBLE),
        metric("n", DataType.LONG),
    ])
    rng = np.random.default_rng(47)
    n = 1200
    cols = {
        "city": rng.choice(["nyc", "sf", "la"], n).tolist(),
        "fare": np.round(rng.uniform(1, 50, n), 2),
        "n": rng.integers(0, 100, n),
    }
    cluster = ProcessCluster(num_servers=2, work_dir=str(tmp))
    cluster.controller.add_schema(schema)
    cluster.controller.add_table(TableConfig("trips"))
    b = SegmentBuilder(schema)
    for i in range(4):
        part = {k: v[i * 300:(i + 1) * 300] for k, v in cols.items()}
        cluster.controller.upload_segment(
            "trips_OFFLINE", b.build(part, str(tmp / "b"), f"trips_{i}"))
    assert wait_until(lambda: cluster.query(
        "SELECT COUNT(*) FROM trips")["resultTable"]["rows"][0][0] == n,
        timeout=30)
    yield cluster, cols
    cluster.shutdown()


def test_plan_pushes_down_filter_and_projection(cluster_with_trips):
    cluster, cols = cluster_with_trips
    reader = PinotReader(cluster.controller_url)
    splits = reader.plan_read("trips", columns=["city", "fare"],
                              filter="fare > 25 AND city = 'nyc'")
    assert splits, "must plan at least one split"
    # every split's SQL carries the pushdown — servers filter before shipping
    for s in splits:
        assert "WHERE fare > 25 AND city = 'nyc'" in s.sql
        assert s.sql.startswith('SELECT "city", "fare" FROM') or \
            s.sql.startswith("SELECT city, fare FROM")
    # all 4 segments covered exactly once, split across BOTH servers
    segs = [seg for s in splits for seg in s.segments]
    assert sorted(segs) == sorted({seg for seg in segs}) and len(segs) == 4
    assert len({s.server_url for s in splits}) == 2


def test_read_table_matches_oracle(cluster_with_trips):
    cluster, cols = cluster_with_trips
    tbl = read_table(cluster.controller_url, "trips",
                     columns=["city", "fare"], filter="fare > 25")
    mask = cols["fare"] > 25
    assert tbl.num_rows == int(mask.sum())
    assert tbl.column_names == ["city", "fare"]
    got = sorted(zip(tbl.column("city").to_pylist(),
                     tbl.column("fare").to_pylist()))
    want = sorted(zip(np.asarray(cols["city"])[mask].tolist(),
                      np.asarray(cols["fare"])[mask].tolist()))
    assert got == pytest.approx(want)
    # arrow types follow the pinot schema
    import pyarrow as pa
    assert tbl.schema.field("fare").type == pa.float64()
    assert tbl.schema.field("city").type == pa.string()


def test_split_subdivision_and_full_scan(cluster_with_trips):
    cluster, cols = cluster_with_trips
    reader = PinotReader(cluster.controller_url)
    fine = reader.plan_read("trips", segments_per_split=1)
    assert len(fine) == 4  # one split per segment
    tbl = reader.read_table("trips", segments_per_split=1)
    assert tbl.num_rows == 1200
    assert tbl.column_names == ["city", "fare", "n"]
    assert sum(tbl.column("n").to_pylist()) == int(np.sum(cols["n"]))


def test_unknown_table_and_column_error(cluster_with_trips):
    cluster, _ = cluster_with_trips
    reader = PinotReader(cluster.controller_url)
    with pytest.raises(KeyError):
        reader.plan_read("nope")
    with pytest.raises(KeyError):
        reader.plan_read("trips", columns=["ghost"])


def test_hybrid_read_respects_time_boundary(tmp_path):
    """Hybrid table: rows copied realtime->offline must appear ONCE — the
    connector applies the same time-boundary split the broker does."""
    import json as _json
    from pinot_tpu.ingest.kafkalite import LogBrokerClient, LogBrokerServer
    from pinot_tpu.schema import date_time
    from pinot_tpu.table import StreamConfig, TableType
    schema = Schema("hyb", [
        dimension("u", DataType.STRING),
        metric("v", DataType.LONG),
        date_time("ts", DataType.LONG),
    ])
    srv = LogBrokerServer()
    try:
        client = LogBrokerClient(srv.bootstrap)
        client.create_topic("hyb_t", 1)
        with ProcessCluster(num_servers=1, work_dir=str(tmp_path)) as cluster:
            cluster.controller.add_schema(schema)
            cluster.controller.add_table(TableConfig(
                "hyb", table_type=TableType.OFFLINE, time_column="ts"))
            cluster.controller.add_table(TableConfig(
                "hyb", table_type=TableType.REALTIME, time_column="ts",
                stream=StreamConfig(stream_type="kafkalite", topic="hyb_t",
                                    properties={"bootstrap": srv.bootstrap},
                                    flush_threshold_rows=10_000)))
            # offline segment covers ts <= 1000 (rows 0..9); realtime holds
            # the SAME old rows plus newer ones (the pre-retention overlap)
            old = {"u": [f"u{i}" for i in range(10)],
                   "v": np.arange(10), "ts": np.arange(901, 1001, 10)}
            cluster.controller.upload_segment(
                "hyb_OFFLINE", SegmentBuilder(schema).build(
                    old, str(tmp_path / "b"), "hyb_0"))
            for i in range(25):
                client.produce("hyb_t", _json.dumps(
                    {"u": f"u{i}", "v": int(i), "ts": 901 + i * 10}))

            def broker_count():
                rows = cluster.query(
                    "SELECT COUNT(*) FROM hyb")["resultTable"]["rows"]
                return rows[0][0] if rows else 0
            assert wait_until(lambda: broker_count() == 25, timeout=30)

            tbl = read_table(cluster.controller_url, "hyb", columns=["ts"])
            assert tbl.num_rows == 25  # overlap counted once
            assert sorted(tbl.column("ts").to_pylist()) == \
                sorted(901 + i * 10 for i in range(25))
    finally:
        srv.stop()


def test_admin_ui_and_query_console(cluster_with_trips):
    """Admin surface: overview with drill-down links, per-table segment page
    (placement + per-server counts = skew diagnosis), task page, and the
    query console's POST /sql broker proxy."""
    import urllib.request
    cluster, cols = cluster_with_trips
    url = cluster.controller_url

    def get(path):
        return urllib.request.urlopen(f"{url}{path}", timeout=10).read().decode()

    overview = get("/ui")
    assert "/ui/table/trips_OFFLINE" in overview
    assert "segments served" in overview

    table_page = get("/ui/table/trips_OFFLINE")
    assert "Segments per server" in table_page
    for i in range(4):
        assert f"trips_{i}" in table_page
    assert "server_0" in table_page and "server_1" in table_page

    tasks_page = get("/ui/tasks")
    assert "Minion tasks" in tasks_page

    console = get("/ui/query")
    assert "Query console" in console and "/sql" in console

    from pinot_tpu.cluster.http_service import post_json
    resp = post_json(f"{url}/sql",
                     {"sql": "SELECT COUNT(*) FROM trips"}, timeout=30)
    assert resp["resultTable"]["rows"][0][0] == 1200
